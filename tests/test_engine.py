"""Graph-collective engine tests: all 8 strategies x several np, numeric
cross-check vs numpy — parity with the reference's integration matrix
(scripts/tests/run-integration-tests.sh: np 1..4 x all strategies)."""

import threading

import numpy as np
import pytest

from kungfu_tpu.comm.engine import CollectiveEngine, build_strategy_graphs
from kungfu_tpu.comm.host import HostChannel
from kungfu_tpu.plan import PeerID, PeerList, Strategy

from tests._util import run_all

BASE_PORT = 25000
_port_gen = [BASE_PORT]


def make_cluster(n, hosts=1):
    """n peers spread over `hosts` logical hosts (all on 127.0.0.1 but with
    distinct host labels is not possible for real sockets, so hosts>1 uses
    port-partitioned groups on the same ip only for graph generation)."""
    _port_gen[0] += n + 2
    base = _port_gen[0]
    peers = PeerList.of(*(PeerID("127.0.0.1", base + i) for i in range(n)))
    chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
    return peers, chans




ALL_STRATEGIES = [s for s in Strategy if s != Strategy.AUTO]


class TestStrategyGraphs:
    @pytest.mark.parametrize("strategy", ALL_STRATEGIES)
    @pytest.mark.parametrize("n", [1, 2, 4, 5])
    def test_graphs_well_formed(self, strategy, n):
        peers = PeerList.of(*(PeerID("h", 10000 + i) for i in range(n)))
        pairs = build_strategy_graphs(strategy, peers)
        assert pairs
        for red, bc in pairs:
            roots = [i for i in range(n) if bc.is_self_loop(i)]
            assert len(roots) == 1


class TestEngine:
    @pytest.mark.parametrize("strategy", ALL_STRATEGIES)
    def test_allreduce_3peers(self, strategy):
        peers, chans = make_cluster(3)
        try:
            engines = [CollectiveEngine(c, peers, strategy) for c in chans]
            data = [np.arange(10, dtype=np.float32) * (i + 1) for i in range(3)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d) for e, d in zip(engines, data)])
            want = sum(data)
            for o in outs:
                np.testing.assert_allclose(o, want, rtol=1e-6)
        finally:
            for c in chans:
                c.close()

    @pytest.mark.parametrize("op,npf", [("min", np.minimum), ("max", np.maximum), ("prod", np.multiply)])
    def test_ops(self, op, npf):
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = [np.array([3.0, -1.0, 2.0], np.float32), np.array([1.0, 5.0, 2.0], np.float32)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d, op=op) for e, d in zip(engines, data)])
            want = npf(data[0], data[1])
            for o in outs:
                np.testing.assert_allclose(o, want)
        finally:
            for c in chans:
                c.close()

    @pytest.mark.parametrize("native_on", ["1", "0"])
    @pytest.mark.parametrize("op", ["sum", "mean"])
    def test_allreduce_inplace(self, monkeypatch, native_on, op):
        """inplace=True reduces into the caller's buffer (NCCL in-place
        analog) on BOTH the native executor and the Python fallback; with
        op='mean' the buffer must hold the divided result, not the sum."""
        monkeypatch.setenv("KF_NATIVE_ENGINE", native_on)
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = [np.arange(8, dtype=np.float32) * (i + 1) for i in range(2)]
            want = data[0] + data[1]
            if op == "mean":
                want = want / 2
            outs = run_all(
                [lambda e=e, d=d: (e.all_reduce(d, op=op, inplace=True), d)
                 for e, d in zip(engines, data)]
            )
            for out, buf in outs:
                np.testing.assert_allclose(out, want, rtol=1e-6)
                # the input buffer was clobbered with the result
                np.testing.assert_allclose(buf, want, rtol=1e-6)
        finally:
            for c in chans:
                c.close()

    def test_mean(self):
        peers, chans = make_cluster(4)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.BINARY_TREE) for c in chans]
            data = [np.full(5, float(i), np.float32) for i in range(4)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d, op="mean") for e, d in zip(engines, data)])
            for o in outs:
                np.testing.assert_allclose(o, np.full(5, 1.5), rtol=1e-6)
        finally:
            for c in chans:
                c.close()

    def test_chunked_multigraph(self):
        """Buffer > 1 MiB: chunks spread across strategy pairs (RING has n
        rotated pairs) and reassemble correctly."""
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.RING) for c in chans]
            rng = np.random.RandomState(0)
            data = [rng.rand(300_000).astype(np.float32) for _ in range(2)]  # 1.2 MB
            outs = run_all([lambda e=e, d=d: e.all_reduce(d) for e, d in zip(engines, data)])
            want = data[0] + data[1]
            for o in outs:
                np.testing.assert_allclose(o, want, rtol=1e-6)
            # both ring rotations saw traffic
            assert sum(b for b, _ in engines[0].stats) == data[0].nbytes
        finally:
            for c in chans:
                c.close()

    def test_broadcast(self):
        peers, chans = make_cluster(3)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = [np.full(4, float(i + 1), np.float32) for i in range(3)]
            outs = run_all(
                [lambda e=e, d=d: e.broadcast(d, root=1) for e, d in zip(engines, data)]
            )
            for o in outs:
                np.testing.assert_allclose(o, np.full(4, 2.0))
        finally:
            for c in chans:
                c.close()

    def test_int_sum(self):
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.TREE) for c in chans]
            data = [np.arange(6, dtype=np.int32), np.ones(6, np.int32)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d) for e, d in zip(engines, data)])
            for o in outs:
                np.testing.assert_array_equal(o, data[0] + data[1])
        finally:
            for c in chans:
                c.close()

    def test_throughput_stats(self):
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = np.ones(100, np.float32)
            run_all([lambda e=e: e.all_reduce(data) for e in engines])
            assert engines[0].throughputs()[0] > 0
        finally:
            for c in chans:
                c.close()

    def test_set_strategy(self):
        peers, chans = make_cluster(2)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            for e in engines:
                e.set_strategy(Strategy.RING)
            data = [np.ones(4, np.float32), np.full(4, 2.0, np.float32)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d) for e, d in zip(engines, data)])
            for o in outs:
                np.testing.assert_allclose(o, np.full(4, 3.0))
        finally:
            for c in chans:
                c.close()


class TestStrategySweepMultiHost:
    """All 8 strategies x both chunk-hash modes on a simulated 2-host
    cluster (loopback aliases), with graph-shape assertions that the
    families are actually distinct (VERDICT round 1: MULTI_STAR had
    aliased CLIQUE)."""

    def _quad_peers(self, base_port):
        return PeerList.of(
            PeerID("127.0.0.1", base_port), PeerID("127.0.0.1", base_port + 1),
            PeerID("127.0.0.2", base_port + 2), PeerID("127.0.0.2", base_port + 3),
        )

    @pytest.mark.parametrize("hash_mode", ["simple", "NAME"])
    @pytest.mark.parametrize("strategy", ALL_STRATEGIES)
    def test_allreduce_2hosts(self, strategy, hash_mode, monkeypatch):
        monkeypatch.setenv("KF_CONFIG_STRATEGY_HASH_METHOD", hash_mode)
        port = 23300 + 10 * ALL_STRATEGIES.index(strategy) + (100 if hash_mode == "NAME" else 0)
        peers = self._quad_peers(port)
        chans = [HostChannel(p, bind_host=p.host) for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, strategy) for c in chans]
            assert engines[0]._hash_name_based == (hash_mode == "NAME")
            rng = np.random.RandomState(1)
            # >1 MiB so chunking + the hash mode are both exercised
            data = [rng.rand(300_000).astype(np.float32) for _ in range(4)]
            outs = run_all(
                [lambda e=e, d=d: e.all_reduce(d, name="grad/w0") for e, d in zip(engines, data)]
            )
            want = sum(data)
            for o in outs:
                np.testing.assert_allclose(o, want, rtol=1e-5)
        finally:
            for c in chans:
                c.close()

    def test_families_distinct(self):
        """MULTI_STAR is host-aware (rotated star-of-masters), CLIQUE is
        per-rank stars — the graph families must differ on a 2-host
        cluster (reference topology.go:117-147)."""
        peers = self._quad_peers(23290)
        ms = build_strategy_graphs(Strategy.MULTI_STAR, peers)
        cl = build_strategy_graphs(Strategy.CLIQUE, peers)
        assert len(ms) == 2  # one per master
        assert len(cl) == 4  # one per rank
        # multi-star rotation 0: master 0 central, local edge 2->3 intact
        bc0 = ms[0][1]
        assert bc0.is_self_loop(0) and 2 in bc0.nexts(0) and 3 in bc0.nexts(2)
        # rotation 1: master 2 central
        bc1 = ms[1][1]
        assert bc1.is_self_loop(2) and 0 in bc1.nexts(2) and 1 in bc1.nexts(0)
        # clique centers are the 4 ranks themselves
        centers = [next(i for i in range(4) if bc.is_self_loop(i)) for _, bc in cl]
        assert centers == [0, 1, 2, 3]

    @pytest.mark.parametrize("strategy,n_cross", [(Strategy.RING, 2), (Strategy.BINARY_TREE_STAR, 1)])
    def test_cross_stage_strategies(self, strategy, n_cross):
        """cross_all_reduce runs its masters stage over ring rotations for
        RING and a binary tree otherwise (reference strategy.go:188-210)."""
        port = 23270 if strategy == Strategy.RING else 23280
        peers = self._quad_peers(port)
        chans = [HostChannel(p, bind_host=p.host) for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, strategy) for c in chans]
            assert len(engines[0]._cross_graphs) == n_cross
            # non-masters (ranks 1, 3) are inert in every cross graph
            for red, bc in engines[0]._cross_graphs:
                for r in (1, 3):
                    assert not red.prevs(r) and not red.nexts(r) and not bc.nexts(r)
            outs = run_all(
                [
                    lambda e=e, i=i: e.cross_all_reduce(np.full(5, i + 1.0, np.float32))
                    for i, e in enumerate(engines)
                ]
            )
            for o in outs:
                np.testing.assert_allclose(o, np.full(5, 10.0))
        finally:
            for c in chans:
                c.close()

    def test_name_hash_pins_tensor_to_strategy(self, monkeypatch):
        monkeypatch.setenv("KF_CONFIG_STRATEGY_HASH_METHOD", "NAME")
        from kungfu_tpu.comm.engine import name_based_hash

        peers = self._quad_peers(23260)
        chans = [HostChannel(p, bind_host=p.host) for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.RING) for c in chans]
            e = engines[0]
            # every chunk of one named tensor picks the same graph pair
            picks = {e._choose(i, "grad/dense0") for i in range(8)}
            assert len(picks) == 1
            assert picks == {name_based_hash("grad/dense0") % len(e._graphs)}
            # different names can land on different pairs
            names = [f"grad/w{i}" for i in range(16)]
            assert len({e._choose(0, n) for n in names}) > 1
        finally:
            for c in chans:
                c.close()


class TestNativeExecutorInterop:
    """The C++ engine executor (kf_engine_all_reduce) against the Python
    chunk loop — same wire protocol, same chunk boundaries."""

    def test_mixed_backend_allreduce(self):
        from kungfu_tpu.comm.host import NativeHostChannel, PyHostChannel
        from kungfu_tpu.native import transport as nt

        if not nt.available():
            pytest.skip("native transport not built")
        peers = PeerList.of(
            PeerID("127.0.0.1", 23420), PeerID("127.0.0.1", 23421),
            PeerID("127.0.0.1", 23422),
        )
        # rank 0/2 native (C++ executor), rank 1 python (fallback loop)
        chans = [
            NativeHostChannel(peers[0], bind_host="127.0.0.1"),
            PyHostChannel(peers[1], bind_host="127.0.0.1"),
            NativeHostChannel(peers[2], bind_host="127.0.0.1"),
        ]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.RING) for c in chans]
            rng = np.random.RandomState(3)
            # >1 MiB: chunk boundaries must agree across implementations
            data = [rng.rand(400_000).astype(np.float32) for _ in range(3)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d, name="t") for e, d in zip(engines, data)])
            want = sum(data)
            for o in outs:
                np.testing.assert_allclose(o, want, rtol=1e-5)
            # stats recorded on the native path too (adaptation windows)
            assert sum(b for b, _ in engines[0].stats) == data[0].nbytes
        finally:
            for c in chans:
                c.close()

    def test_native_executor_all_ops_dtypes(self):
        from kungfu_tpu.comm.host import NativeHostChannel
        from kungfu_tpu.native import transport as nt

        if not nt.available():
            pytest.skip("native transport not built")
        peers = PeerList.of(
            PeerID("127.0.0.1", 23430), PeerID("127.0.0.1", 23431),
        )
        chans = [NativeHostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            cases = [
                ("sum", np.float64), ("min", np.float32), ("max", np.int32),
                ("prod", np.float32), ("mean", np.float32),
            ]
            for op, dt in cases:
                data = [
                    (np.arange(1, 7) * (i + 1)).astype(dt) for i in range(2)
                ]
                outs = run_all(
                    [lambda e=e, d=d: e.all_reduce(d, op=op) for e, d in zip(engines, data)]
                )
                ref = {
                    "sum": data[0] + data[1], "min": np.minimum(*data),
                    "max": np.maximum(*data), "prod": data[0] * data[1],
                    "mean": (data[0] + data[1]) / 2,
                }[op]
                for o in outs:
                    np.testing.assert_allclose(o, ref, rtol=1e-6)
        finally:
            for c in chans:
                c.close()


class TestSessionSurfaceParity:
    """Reduce/Gather/AllGather/Local*/CrossAllReduce (reference Session API)."""

    @pytest.fixture
    def quad(self):
        # two simulated hosts (loopback aliases) x two peers each
        peers = PeerList.of(
            PeerID("127.0.0.1", 23200), PeerID("127.0.0.1", 23201),
            PeerID("127.0.0.2", 23202), PeerID("127.0.0.2", 23203),
        )
        chans = [HostChannel(p, bind_host=p.host) for p in peers]
        engines = [CollectiveEngine(c, peers, strategy=Strategy.STAR) for c in chans]
        yield peers, engines
        for c in chans:
            c.close()

    def _run(self, engines, fn):
        outs = [None] * len(engines)
        errs = []

        def go(i):
            try:
                outs[i] = fn(i, engines[i])
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=go, args=(i,)) for i in range(len(engines))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        if errs:
            raise errs[0]
        return outs

    def test_reduce_to_root(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.reduce(np.full(3, i + 1, np.float32), root=0)
        )
        np.testing.assert_allclose(outs[0], np.full(3, 10.0))  # 1+2+3+4
        np.testing.assert_allclose(outs[2], np.full(3, 3.0))  # unchanged input

    def test_gather(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.gather(np.full(2, i, np.int32), root=0)
        )
        np.testing.assert_array_equal(
            outs[0], np.stack([np.full(2, i, np.int32) for i in range(4)])
        )
        assert outs[1] is None and outs[3] is None

    def test_all_gather(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.all_gather(np.full(2, i, np.float32))
        )
        expect = np.stack([np.full(2, i, np.float32) for i in range(4)])
        for o in outs:
            np.testing.assert_array_equal(o, expect)

    def test_local_reduce_and_broadcast(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.local_reduce(np.full(2, i + 1.0, np.float32))
        )
        np.testing.assert_allclose(outs[0], np.full(2, 3.0))  # host A: 1+2
        np.testing.assert_allclose(outs[2], np.full(2, 7.0))  # host B: 3+4
        np.testing.assert_allclose(outs[1], np.full(2, 2.0))  # unchanged
        outs = self._run(
            engines,
            lambda i, e: e.local_broadcast(
                np.full(2, 100.0 + i, np.float32) if i in (0, 2) else np.zeros(2, np.float32)
            ),
        )
        np.testing.assert_allclose(outs[1], np.full(2, 100.0))
        np.testing.assert_allclose(outs[3], np.full(2, 102.0))

    def test_cross_all_reduce(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.cross_all_reduce(np.full(3, i + 1.0, np.float32))
        )
        for o in outs:
            np.testing.assert_allclose(o, np.full(3, 10.0))

    def test_cross_all_reduce_mean(self, quad):
        _, engines = quad
        outs = self._run(
            engines, lambda i, e: e.cross_all_reduce(np.full(3, i + 1.0, np.float32), op="mean")
        )
        for o in outs:
            np.testing.assert_allclose(o, np.full(3, 2.5))


class TestEngineReduceScatter:
    """Host-plane reduce-scatter: the ZeRO-2 gradient collective for
    one-process-per-rank worlds (engine analog of the device plane's
    Communicator.reduce_scatter)."""

    @pytest.mark.parametrize("n", [2, 3, 4])
    def test_chunks_reduce_exactly(self, n):
        peers, chans = make_cluster(n)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR)
                       for c in chans]
            data = [np.arange(10, dtype=np.float32) * (i + 1)
                    for i in range(n)]
            outs = run_all([
                lambda e=e, d=d: e.reduce_scatter(d, name="rs1")
                for e, d in zip(engines, data)])
            chunk = -(-10 // n)
            padded = np.zeros(chunk * n, np.float32)
            padded[:10] = sum(data)
            for r, o in enumerate(outs):
                assert o.shape == (chunk,)
                np.testing.assert_allclose(
                    o, padded[r * chunk:(r + 1) * chunk], rtol=1e-6)
        finally:
            for c in chans:
                c.close()

    def test_mean_op(self):
        peers, chans = make_cluster(3)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR)
                       for c in chans]
            data = [np.full(6, float(i + 1), np.float32) for i in range(3)]
            outs = run_all([
                lambda e=e, d=d: e.reduce_scatter(d, op="mean", name="rs2")
                for e, d in zip(engines, data)])
            for o in outs:
                np.testing.assert_allclose(o, np.full(2, 2.0), rtol=1e-6)
        finally:
            for c in chans:
                c.close()

    def test_matches_allreduce_slice(self):
        """reduce_scatter(x)[my chunk] == all_reduce(x)[my chunk] — the
        decomposition identity the ZeRO comm-volume claim rests on."""
        peers, chans = make_cluster(3)
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR)
                       for c in chans]
            rng = np.random.RandomState(0)
            data = [rng.randn(9).astype(np.float32) for _ in range(3)]
            full = run_all([
                lambda e=e, d=d: e.all_reduce(d, name="ar")
                for e, d in zip(engines, data)])
            scat = run_all([
                lambda e=e, d=d: e.reduce_scatter(d, name="rs3")
                for e, d in zip(engines, data)])
            for r in range(3):
                np.testing.assert_allclose(
                    scat[r], full[r][r * 3:(r + 1) * 3], rtol=1e-5)
        finally:
            for c in chans:
                c.close()

    def test_bad_op_rejected(self):
        peers, chans = make_cluster(2)
        try:
            eng = CollectiveEngine(chans[0], peers, Strategy.STAR)
            with pytest.raises(ValueError):
                eng.reduce_scatter(np.ones(4, np.float32), op="median")
        finally:
            for c in chans:
                c.close()
