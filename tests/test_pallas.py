"""Pallas kernel tests (interpreter mode on the CPU test platform).

Numerical cross-check against the plain-XLA attention — the same
"pluggable impls compared against each other" strategy the reference
uses for its collectives (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kungfu_tpu.models.transformer import default_attention
from kungfu_tpu.ops.pallas import flash_attention, make_flash_attn


def _rand_qkv(b, h, s, d, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, h, s, d)), dtype) for _ in range(3)
    )


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_xla_attention(self, causal):
        q, k, v = _rand_qkv(2, 2, 256, 32)
        ref = default_attention(q, k, v, causal)
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=2e-5)

    def test_ragged_seq_len_padding(self):
        # S not a multiple of the block sizes exercises the tail mask
        q, k, v = _rand_qkv(1, 2, 200, 32)
        ref = default_attention(q, k, v, True)
        got = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=2e-5)

    def test_small_blocks(self):
        q, k, v = _rand_qkv(1, 1, 128, 16)
        ref = default_attention(q, k, v, True)
        got = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=64, interpret=True
        )
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=2e-5)

    def test_three_dim_input(self):
        q, k, v = _rand_qkv(1, 3, 128, 16)
        got3 = flash_attention(
            q.reshape(3, 128, 16), k.reshape(3, 128, 16), v.reshape(3, 128, 16),
            causal=True, interpret=True,
        )
        got4 = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got3), np.asarray(got4).reshape(3, 128, 16), atol=1e-6
        )


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_xla_attention(self, causal):
        q, k, v = _rand_qkv(1, 2, 160, 32, seed=1)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(default_attention(q, k, v, causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
            )


class TestFlashBackwardPallasKernels:
    """The round-3 Pallas backward kernels (dQ + dK/dV), forced on via
    KF_PALLAS_BWD=pallas and run in interpret mode, cross-checked against
    plain-XLA autodiff AND the blocked-jnp reference backward."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_grads_match_xla(self, monkeypatch, causal):
        monkeypatch.setenv("KF_PALLAS_BWD", "pallas")
        q, k, v = _rand_qkv(1, 2, 160, 32, seed=3)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=causal, interpret=True) ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(default_attention(q, k, v, causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
            )

    def test_kernel_matches_blocked_jnp(self, monkeypatch):
        """Bit-level-ish agreement between the two backward impls on the
        same saved (out, lse) — isolates the kernels from fwd noise,
        including the ragged-tail padding path (S=200 vs 128-blocks)."""
        from kungfu_tpu.ops.pallas.attention import (
            _bwd_blocked, _bwd_pallas, _fwd_call,
        )

        rng = np.random.default_rng(7)
        bh, s, d = 2, 200, 32
        q, k, v, do = (
            jnp.asarray(rng.normal(size=(bh, s, d)), jnp.float32)
            for _ in range(4)
        )
        out, lse = _fwd_call(q, k, v, True, 128, 128, True)
        ref = _bwd_blocked(q, k, v, out, lse, do, True, 128)
        got = _bwd_pallas(q, k, v, out, lse, do, True, 128, 128, True)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
            )

    def test_kernel_small_blocks_noncausal(self, monkeypatch):
        monkeypatch.setenv("KF_PALLAS_BWD", "pallas")
        q, k, v = _rand_qkv(1, 1, 96, 16, seed=5)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=False, block_q=32, block_k=32,
                    interpret=True,
                )
            )

        def loss_ref(q, k, v):
            return jnp.sum(default_attention(q, k, v, False))

        gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
            )


class TestTransformerIntegration:
    def test_flash_as_attn_fn(self):
        from kungfu_tpu.models.transformer import Transformer, TransformerConfig

        # f32 activations: compares the attention math itself; in bf16 the
        # two impls' (equally valid) rounding diverges through the layers
        cfg = TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=2, d_ff=128,
            max_seq=64, causal=True, dtype="float32",
        )
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, size=(2, 64)), jnp.int32
        )
        ref = model.apply(params, ids)
        got = model.apply(params, ids, attn_fn=make_flash_attn())
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(got), atol=2e-3
        )


class TestFusedCrossEntropy:
    def _data(self, b=2, s=100, v=1000, seed=0):
        rng = np.random.default_rng(seed)
        logits = jnp.asarray(rng.normal(size=(b, s, v)) * 3, jnp.float32)
        targets = jnp.asarray(rng.integers(0, v, size=(b, s)), jnp.int32)
        return logits, targets

    def _ref(self, logits, targets):
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, targets[..., None], -1).squeeze(-1)

    def test_matches_log_softmax(self):
        from kungfu_tpu.ops.pallas import softmax_cross_entropy

        logits, targets = self._data()
        got = softmax_cross_entropy(logits, targets, interpret=True)
        np.testing.assert_allclose(
            np.asarray(self._ref(logits, targets)), np.asarray(got), atol=1e-4
        )

    def test_grads_match(self):
        from kungfu_tpu.ops.pallas import softmax_cross_entropy

        logits, targets = self._data(b=1, s=64, v=700, seed=1)
        gk = jax.grad(lambda x: jnp.mean(softmax_cross_entropy(x, targets, interpret=True)))(logits)
        gr = jax.grad(lambda x: jnp.mean(self._ref(x, targets)))(logits)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-6)

    def test_unmasked_fast_path(self, monkeypatch):
        """block_v dividing the vocab takes the masked=False branch — the
        production LM-head shape (V=32768, block_v=2048) and the path the
        other tests' odd vocabs never reach.  KF_PALLAS_BWD=pallas forces
        the backward KERNEL (not the blocked-jnp fallback) so its
        masked=False branch is covered too."""
        from kungfu_tpu.ops.pallas import softmax_cross_entropy

        monkeypatch.setenv("KF_PALLAS_BWD", "pallas")
        logits, targets = self._data(b=1, s=64, v=512, seed=2)
        got = softmax_cross_entropy(
            logits, targets, block_n=32, block_v=256, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(self._ref(logits, targets)), np.asarray(got), atol=1e-4
        )
        gk = jax.grad(lambda x: jnp.mean(softmax_cross_entropy(
            x, targets, block_n=32, block_v=256, interpret=True)))(logits)
        gr = jax.grad(lambda x: jnp.mean(self._ref(x, targets)))(logits)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-6)

    def test_bf16_logits(self):
        from kungfu_tpu.ops.pallas import softmax_cross_entropy

        logits, targets = self._data(v=512)
        got = softmax_cross_entropy(logits.astype(jnp.bfloat16), targets, interpret=True)
        ref = self._ref(logits.astype(jnp.bfloat16).astype(jnp.float32), targets)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=1e-3)

    def test_pallas_bwd_kernel_grads_match(self, monkeypatch):
        """The round-3 xent backward KERNEL (KF_PALLAS_BWD=pallas) matches
        XLA autodiff of the logsumexp formulation, incl. ragged vocab."""
        from kungfu_tpu.ops.pallas import softmax_cross_entropy

        monkeypatch.setenv("KF_PALLAS_BWD", "pallas")
        rng = np.random.default_rng(11)
        logits = jnp.asarray(rng.normal(size=(96, 700)), jnp.float32)
        targets = jnp.asarray(rng.integers(0, 700, 96), jnp.int32)

        def loss_fused(x):
            return softmax_cross_entropy(x, targets, interpret=True).mean()

        def loss_ref(x):
            lse = jax.scipy.special.logsumexp(x, axis=-1)
            gold = jnp.take_along_axis(x, targets[:, None], axis=-1)[:, 0]
            return (lse - gold).mean()

        gf = jax.grad(loss_fused)(logits)
        gr = jax.grad(loss_ref)(logits)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=2e-5)

    def test_model_loss_fused_matches(self, monkeypatch):
        from kungfu_tpu.models.transformer import Transformer, TransformerConfig

        cfg = TransformerConfig(
            vocab_size=256, d_model=64, n_layers=1, n_heads=2, d_ff=128,
            max_seq=32, causal=True, dtype="float32",
        )
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        data = np.random.default_rng(0).integers(0, 256, size=(2, 33))
        batch = (jnp.asarray(data[:, :-1], jnp.int32), jnp.asarray(data[:, 1:], jnp.int32))
        from kungfu_tpu.ops.pallas.xent import XENT_ENV

        monkeypatch.setenv("KF_TPU_XENT", "xla")
        XENT_ENV.reload()
        ref = model.loss(params, batch)
        monkeypatch.setenv("KF_TPU_XENT", "fused")
        XENT_ENV.reload()
        got = model.loss(params, batch)
        np.testing.assert_allclose(float(ref), float(got), atol=1e-5)


class TestXentRouting:
    """Per-shape kernel-vs-XLA auto routing (round-3 VERDICT item 3:
    auto sent EVERY TPU caller to the kernel, including training shapes
    where XLA's fused backward is ~2x faster)."""

    def test_training_routes_by_memory_budget(self):
        from kungfu_tpu.ops.pallas.xent import _route_fused

        # the settled micro-bench shape (N=8192, V=32768, bf16): XLA's
        # residual estimate is ~1.5 GiB < budget -> XLA wins the train
        # path (it measured 2.3 vs 4.7 ms)
        assert _route_fused(8192, 32768, 2, training=True) is False
        # the batch-8 LM shape that OOMs the XLA variant -> kernel
        assert _route_fused(16384, 50304, 2, training=True) is True

    def test_eval_routes_by_streaming_scale(self):
        from kungfu_tpu.ops.pallas.xent import _route_fused

        # fwd-only: kernel measured ~2x at HBM scale
        assert _route_fused(8192, 32768, 2, training=False) is True
        # tiny logits: pallas call overhead loses, route XLA
        assert _route_fused(128, 1024, 4, training=False) is False

    def test_env_budget_override(self, monkeypatch):
        """The knobs are launch-set (read at import — the
        recompile-hazard hoist): env mutations take effect through
        ``XENT_ENV.reload()``, never at trace time."""
        from kungfu_tpu.ops.pallas.xent import XENT_ENV, _route_fused

        monkeypatch.setenv("KF_XENT_XLA_BUDGET_MB", "1")
        XENT_ENV.reload()
        assert _route_fused(1024, 1024, 2, training=True) is True
        monkeypatch.setenv("KF_XENT_XLA_BUDGET_MB", "1048576")
        # without a reload the mutation is invisible — launch-set for real
        assert _route_fused(1024, 1024, 2, training=True) is True
        XENT_ENV.reload()
        assert _route_fused(16384, 50304, 2, training=True) is False

    def test_forced_modes_bypass_routing(self, monkeypatch):
        """KF_TPU_XENT=fused/plain still win over the shape router, and
        both compute the same value."""
        import kungfu_tpu.ops.pallas.xent as X

        logits = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 64)), jnp.float32)
        targets = jnp.asarray([1, 2, 3, 4], jnp.int32)
        monkeypatch.setenv("KF_TPU_XENT", "plain")
        X.XENT_ENV.reload()
        ref = float(X.token_nll(logits, targets))
        monkeypatch.setenv("KF_TPU_XENT", "fused")
        X.XENT_ENV.reload()
        got = float(X.token_nll(logits, targets))
        np.testing.assert_allclose(ref, got, atol=1e-5)


class TestDefaultBlocks:
    """Adaptive flash block resolution (round-3 v5e sweep: big K/V tiles,
    but never mostly-padding ones)."""

    def test_sweep_winners_at_long_seq(self):
        from kungfu_tpu.ops.pallas.attention import _default_blocks

        assert _default_blocks(2048, None, None) == (256, 1024)
        assert _default_blocks(8192, None, None) == (256, 1024)

    def test_short_seq_never_pads_a_whole_tile(self):
        from kungfu_tpu.ops.pallas.attention import _default_blocks

        assert _default_blocks(128, None, None) == (128, 128)
        assert _default_blocks(100, None, None) == (128, 128)
        assert _default_blocks(300, None, None) == (128, 128)

    def test_padding_allowance_caps_waste(self):
        from kungfu_tpu.ops.pallas.attention import _default_blocks

        # S=1152 with a 1024 block would pad to 2048 (~78% waste)
        bq, bk = _default_blocks(1152, None, None)
        assert bk <= 256
        # allowance scales with S: 1536 tolerates a 512 tile, not 1024
        assert _default_blocks(1536, None, None)[1] == 512

    def test_explicit_blocks_pass_through(self):
        from kungfu_tpu.ops.pallas.attention import _default_blocks

        assert _default_blocks(2048, 32, 64) == (32, 64)
        assert _default_blocks(2048, None, 64) == (256, 64)


class TestFusedLMHead:
    """lm_head.py — the LM-head matmuls fused into the xent fwd+bwd:
    loss and BOTH gradients must match the plain logits path."""

    def _ref(self, h, w, t):
        logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
        lse = jax.scipy.special.logsumexp(logits, -1)
        tl = jnp.take_along_axis(logits, t[:, None], -1)[:, 0]
        return lse - tl

    @pytest.mark.parametrize("shape", [
        (16, 32, 256),    # aligned
        (20, 48, 300),    # ragged N, D, V (pad paths in every dim)
        (8, 128, 1000),   # ragged V only
    ])
    def test_loss_and_grads_match_reference(self, shape):
        from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

        n, d, v = shape
        rng = np.random.default_rng(1)
        h = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
        t = jnp.asarray(rng.integers(0, v, n), jnp.int32)

        l_ref = self._ref(h, w, t)
        l_k = lm_head_nll(h, w, t, block_n=8, block_v=128)
        np.testing.assert_allclose(np.asarray(l_k), np.asarray(l_ref),
                                   rtol=2e-5, atol=1e-6)

        g_ref = jax.grad(lambda h, w: jnp.mean(self._ref(h, w, t)),
                         argnums=(0, 1))(h, w)
        g_k = jax.grad(
            lambda h, w: jnp.mean(lm_head_nll(h, w, t, block_n=8,
                                              block_v=128)),
            argnums=(0, 1))(h, w)
        for a, b in zip(g_k, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    def test_bf16_inputs(self):
        from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

        rng = np.random.default_rng(2)
        n, d, v = 16, 64, 384
        h = jnp.asarray(rng.standard_normal((n, d)), jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.bfloat16)
        t = jnp.asarray(rng.integers(0, v, n), jnp.int32)
        l_ref = self._ref(h, w, t)
        loss, grads = jax.value_and_grad(
            lambda h, w: jnp.mean(lm_head_nll(h, w, t, block_n=8,
                                              block_v=128)),
            argnums=(0, 1))(h, w)
        np.testing.assert_allclose(float(loss), float(jnp.mean(l_ref)),
                                   rtol=5e-3)
        assert grads[0].dtype == jnp.bfloat16
        assert grads[1].dtype == jnp.bfloat16
        g_ref = jax.grad(lambda h, w: jnp.mean(self._ref(h, w, t)),
                         argnums=(0, 1))(h, w)
        for a, b in zip(grads, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=0.1, atol=5e-3)

    def test_leading_batch_dims(self):
        from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

        rng = np.random.default_rng(3)
        b, s, d, v = 2, 10, 32, 200
        h = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
        t = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)
        out = lm_head_nll(h, w, t, block_n=8, block_v=128)
        assert out.shape == (b, s)
        ref = self._ref(h.reshape(-1, d), w, t.reshape(-1)).reshape(b, s)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=1e-6)

    def test_model_hidden_path_matches_apply(self):
        """Transformer.hidden + lm_head_nll == token_nll over apply's
        logits — the bench contestant computes the same training loss."""
        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

        cfg = TransformerConfig(vocab_size=128, d_model=32, n_layers=1,
                                n_heads=2, d_ff=64, max_seq=16,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        ids = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
        logits = model.apply(params, ids)
        lse_ref = -jnp.take_along_axis(
            jax.nn.log_softmax(logits), tgt[..., None], -1).squeeze(-1)
        h = model.hidden(params, ids)
        fused = lm_head_nll(h, params["head"]["w"], tgt, block_n=8,
                            block_v=128)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(lse_ref),
                                   rtol=2e-5, atol=1e-5)

    def test_model_loss_lm_head_switch(self, monkeypatch):
        """KF_TPU_LM_HEAD=fused routes Transformer.loss through the
        fused head; the value matches the plain path."""
        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)

        cfg = TransformerConfig(vocab_size=128, d_model=32, n_layers=1,
                                n_heads=2, d_ff=64, max_seq=16,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        batch = (jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32),
                 jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32))
        monkeypatch.setenv("KF_TPU_LM_HEAD", "plain")
        plain = float(model.loss(params, batch, train=True))
        monkeypatch.setenv("KF_TPU_LM_HEAD", "fused")
        fused = float(model.loss(params, batch, train=True))
        np.testing.assert_allclose(fused, plain, rtol=2e-5)
        monkeypatch.setenv("KF_TPU_LM_HEAD", "bogus")
        with pytest.raises(ValueError, match="KF_TPU_LM_HEAD"):
            model.loss(params, batch)

    @pytest.mark.slow  # ~16s: fuzz sweep recompiles per shape
    def test_random_shape_sweep(self):
        """Randomized ragged shapes and block sizes: loss + grads must
        match the reference everywhere (pad/mask path fuzz)."""
        from kungfu_tpu.ops.pallas.lm_head import lm_head_nll

        rng = np.random.default_rng(11)
        for _ in range(5):
            n = int(rng.integers(1, 40))
            d = int(rng.integers(8, 96))
            v = int(rng.integers(16, 520))
            bn = int(rng.choice([8, 16, 32]))
            bv = int(rng.choice([128, 256]))
            h = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
            w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
            t = jnp.asarray(rng.integers(0, v, n), jnp.int32)
            msg = f"n={n} d={d} v={v} bn={bn} bv={bv}"
            l_k = lm_head_nll(h, w, t, block_n=bn, block_v=bv)
            np.testing.assert_allclose(
                np.asarray(l_k), np.asarray(self._ref(h, w, t)),
                rtol=2e-5, atol=1e-5, err_msg=msg)
            g_ref = jax.grad(lambda h, w: jnp.mean(self._ref(h, w, t)),
                             argnums=(0, 1))(h, w)
            g_k = jax.grad(
                lambda h, w: jnp.mean(lm_head_nll(h, w, t, block_n=bn,
                                                  block_v=bv)),
                argnums=(0, 1))(h, w)
            for a, b in zip(g_k, g_ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=msg)
