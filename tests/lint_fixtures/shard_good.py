"""Fixture: compliant sharding idioms — the kf-shard rules must pass
every one of these untouched."""

import functools

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def partial_form():
    """functools.partial(shard_map, mesh=...) binds the mesh too."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2), ("x", "y"))
    smap = functools.partial(shard_map, mesh=mesh, in_specs=(P("x"),),
                             out_specs=P("x"))

    def body(a):
        return jax.lax.psum(a, "y")

    return smap(body)


def nested_sub_mesh():
    """Inner shard_map over a sub-mesh: the OUTER axis stays live."""
    outer = Mesh(np.array(jax.devices()), ("x",))
    inner = Mesh(np.array(jax.devices()[:2]), ("y",))

    def outer_body(a):
        def inner_body(b):
            s = jax.lax.psum(b, "y")       # inner axis
            return jax.lax.psum(s, "x")    # outer axis, still bound

        return shard_map(inner_body, mesh=inner, in_specs=(P("y"),),
                         out_specs=P("y"))(a)

    return shard_map(outer_body, mesh=outer, in_specs=(P("x"),),
                     out_specs=P("x"))


def shared(a, axis):
    """Axis parameter: each caller supplies its own axis — dynamic,
    checked at the call sites that pass literals."""
    return jax.lax.psum(a, axis)


def two_meshes():
    """One helper reached from two meshes with DIFFERENT axis sets —
    per-context environments must not cross-contaminate."""
    mx = Mesh(np.array(jax.devices()), ("x",))
    my = Mesh(np.array(jax.devices()), ("y",))

    def bx(a):
        return jax.lax.psum(shared(a, "x"), "x")

    def by(a):
        return jax.lax.psum(shared(a, "y"), "y")

    fx = shard_map(bx, mesh=mx, in_specs=(P("x"),), out_specs=P())
    fy = shard_map(by, mesh=my, in_specs=(P(None, "y"),), out_specs=P())
    return fx, fy


def unconstrained():
    """PartitionSpec(None, 'x'): None dims are unconstrained and legal."""
    mesh = Mesh(np.array(jax.devices()), ("x",))

    def body(a):
        return a

    return shard_map(body, mesh=mesh, in_specs=(P(None, "x"),),
                     out_specs=P(None, "x"))
