"""Fixture: a host sync two helpers deep.  The pre-callgraph jit-sync
walked ONE level of module-local helpers and missed this; the fixpoint
version reaches it and attributes it to the jitted root."""

import jax


@jax.jit
def step(x):
    return level1(x)


def level1(x):
    return level2(x)


def level2(x):
    return x.item()                # VIOLATION: depth 2 from the jit root
