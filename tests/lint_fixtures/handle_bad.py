"""handle-discipline fixture: every shape the rule must catch."""
import numpy as np


def dropped(engine, x):
    engine.all_reduce_async(x)          # line 6: handle dropped
    return x


def never_waited(engine, x):
    h = engine.reduce_scatter_async(x)  # line 11: never waited
    total = np.sum(x)
    return total


def early_return_leak(engine, x, flag):
    h = engine.all_reduce_async(x)      # line 17: not waited on all paths
    if flag:
        return None                     # leaks h
    return h.wait()


def one_sided_branch(engine, x, flag):
    h = engine.all_gather_async(x)      # line 24: not waited on all paths
    if flag:
        out = h.wait()
    else:
        out = x                         # this path leaks h
    return out


def held_across_resize(engine, peer, state, schedule, params, x):
    h = engine.all_reduce_async(x)
    state, params, stop = elastic_step(  # line 34: fence while in flight
        peer, state, schedule, params)
    out = h.wait()
    return out, state, params, stop


def held_across_shrink(engine, peer, x):
    h = engine.reduce_scatter_async(x)
    shrink_to_survivors(peer, [2])       # line 42: fence while in flight
    return h.wait()


def held_across_worker_dead(engine, router, x):
    h = engine.all_reduce_async(x)
    router.mark_worker_dead(2)           # line 48: serving fence in flight
    return h.wait()


def held_across_stage_recarve(engine, boundary, peer, x):
    h = engine.send_async(1, x, "pp.act")
    boundary.recarve(2, peer=peer)       # stage re-carve fence in flight
    return h.wait()


def held_across_recarve_helper(engine, peer, boundary, old_workers, x):
    h = engine.recv_async(0, "pp.grad")
    recarve_stages_after_shrink(          # re-carve driver in flight
        peer, boundary, old_workers)
    return h.wait()


def recarve_stages_after_shrink(peer, boundary, old_workers):
    return None


def elastic_step(peer, state, schedule, params):
    return state, params, False


def shrink_to_survivors(peer, dead):
    return True
