"""Fixture: seeded recompile-hazard violations (never imported)."""

import os

import jax


@jax.jit
def traced(x, peers):
    n = jax.device_count()                     # VIOLATION: world baked in
    m = len(peers)                             # VIOLATION: peer-list length
    mode = os.environ.get("KF_FIX_MODE", "a")  # VIOLATION: env read
    ok = jax.device_count()  # kflint: allow(recompile-hazard) — doc'd
    return x * n * m * len(mode) * ok


def build_step():
    world = jax.device_count()

    @jax.jit
    def step(x):
        return x / world                       # VIOLATION: closure leak

    return step


def static_hazards():
    def f(params, batch):
        return params, batch

    a = jax.jit(f, static_argnums=(1,))        # VIOLATION: batch varies
    b = jax.jit(f, static_argnums=(7,))        # VIOLATION: out of range
    c = jax.jit(f, static_argnames="batch")    # VIOLATION: varying name
    return a, b, c


def epoch_scoped(comm):
    n = comm.size  # ok: a Communicator is an immutable mesh epoch —
    # resize builds a new one and the step is rebuilt with it

    @jax.jit
    def step(x):
        return x / n

    return step
