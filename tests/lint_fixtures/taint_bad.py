"""Seeded replay-taint violations (tests/test_det.py pins the line
numbers below — keep edits append-only)."""
import json
import time
import uuid


def _stamp():
    # entropy source, two calls below the sink
    return time.time()


def _token():
    return f"run-{_stamp()}"


def two_calls_deep(peer, workers):
    # BAD: time.time() -> _stamp -> _token -> consensus payload; the
    # digest differs on every replay
    peer.channel.consensus_bytes(_token().encode(), workers, name="boot")


def _tag_for(suffix):
    # pure formatter: taint flows param -> return
    return f"kf.win.{suffix}"


def param_flow(peer, workers, blob):
    # BAD: uuid4 through a helper into a rendezvous name — the tag
    # never rendezvouses across ranks, and never replays
    nonce = uuid.uuid4()
    peer.channel.gather_bytes(blob, workers, name=_tag_for(nonce))


def branch_sanitizer(peer, workers, fast):
    # BAD: the else branch keeps the wall-clock tag; sanitizing ONE
    # branch must not launder the other
    if fast:
        tag = "steady"
    else:
        tag = f"w{time.monotonic()}"
    peer.channel.barrier(workers, name=tag)


def container_round_trip(peer, workers):
    # BAD: entropy stored into a dict, serialized, and committed as a
    # manifest-style consensus payload
    meta = {"step": 3}
    meta["issued"] = time.time()
    peer.channel.consensus_bytes(json.dumps(meta).encode(), workers,
                                 name="meta")


def list_append_round_trip(peer, workers, blob):
    # BAD: entropy appended into a list that becomes the tag
    parts = ["kf"]
    parts.append(str(time.perf_counter()))
    peer.channel.barrier(workers, name=".".join(parts))


def agree_one_branch(peer, workers, blob):
    # BAD: the agreement op sanitizes only the cached branch; the
    # fallback still commits a rank-local wall-clock read
    if blob:
        digest = peer.channel.consensus_bytes(blob, workers, name="d")
    else:
        digest = str(time.time_ns()).encode()
    peer.channel.consensus_bytes(digest, workers, name="install")


def waived_probe(peer, workers, blob):
    # suppressed: a deliberately local debug tag, documented here
    peer.channel.gather_bytes(blob, workers, name=f"dbg.{time.time()}")  # kflint: allow(replay-taint)
