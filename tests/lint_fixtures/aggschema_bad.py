"""agg-schema fixture: typo'd / dynamic snapshot+view field names."""

from kungfu_tpu.monitor import aggregator
from kungfu_tpu.monitor.aggregator import field as fld, make_snapshot


def good_reads(view):
    step = aggregator.field(view, "step")  # in schema: clean
    return step, fld(view, "straggler")  # through the alias: clean


def typo_read(view):
    return aggregator.field(view, "stragler")  # typo: flagged


def dynamic_read(view, k):
    return fld(view, k)  # dynamic: flagged


def no_name(view):
    return aggregator.field(view)  # missing name: flagged


def good_snapshot():
    return make_snapshot(rank=0, step=3)  # literal schema fields: clean


def typo_snapshot():
    return make_snapshot(rank=0, stepp=3)  # typo'd field: flagged


def splat_snapshot(extra):
    return make_snapshot(rank=0, **extra)  # dynamic splat: flagged


def waived(view, k):
    return aggregator.field(view, k)  # kflint: allow(agg-schema)


class Unrelated:
    def field(self, *a):
        return self

    def make_snapshot(self, *a):
        return self


def not_the_aggregator():
    u = Unrelated()
    u.field("whatever")  # other receiver: NOT flagged
    u.make_snapshot(bogus=1)


def view_only_snapshot():
    # "stale" is a VIEW field — field() may read it, but make_snapshot()
    # rejects it at runtime, so lint must too: flagged
    return make_snapshot(rank=0, stale=True)
