"""Seeded reduction-order violations.  tests/test_det.py copies this
file under ``kungfu_tpu/ops/`` (a bitwise-pinned path) — keep edits
append-only."""


def set_bucket_fold(widths, slabs):
    # BAD: appending under set iteration builds an ordered artifact
    # from an unordered order
    parts = []
    off = 0
    for w in set(widths):
        parts.append(slabs[off:off + w])
        off += w
    return parts


def set_literal_fold(grads):
    # BAD: float accumulation over a set literal
    total = 0.0
    for k in {"wq", "wk", "wv"}:
        total += grads[k]
    return total


def sum_over_set(vals):
    # BAD: bare sum() folds in Python iteration order
    return sum(v * v for v in set(vals))


def dict_bucket_fold(buckets):
    # BAD (pinned dirs): dict insertion order is geometry-shaped —
    # a restart onto another world size builds the buckets in another
    # order
    acc = 0.0
    for name, val in buckets.items():
        acc += val
    return acc


def order_taint_via_name(ranks):
    # BAD: the set order taint rides the variable
    survivors = set(ranks)
    csv = []
    for r in survivors:
        csv.append(str(r))
    return ",".join(csv)


def waived_fold(buckets):
    # suppressed: documented order-insensitive integer count
    n = 0
    for k in buckets.keys():
        n += 1  # kflint: allow(reduction-order)
    return n
