"""proto-verify fixture: handle-across-fence cycle — each arm posts a
recv, fences on it (drain_async), and only THEN sends the frame the
peer's fence is waiting for.  Both ranks block inside the fence."""
import numpy as np


def proto_entry_mirror(engine, me, left, right, payload):
    if me % 2 == 0:
        engine.recv_async(right, "kf.cyc.even")
        engine.drain_async()
        engine.send_async(left, payload, "kf.cyc.odd")
    else:
        engine.recv_async(left, "kf.cyc.odd")
        engine.drain_async()
        engine.send_async(right, payload, "kf.cyc.even")
