"""retry-discipline fixture: the shipped bug shapes, plus compliant
loops that must NOT be flagged.  Line numbers are asserted in
tests/test_lint.py — edit with care."""

import random
import time
import urllib.request

from kungfu_tpu.utils.retry import sleep_backoff


def unbounded_constant_hammer(url):
    # the elastic-resize bug: every worker, forever, every 0.2s
    while True:  # line 14: unbounded
        try:
            return urllib.request.urlopen(url, timeout=5)
        except OSError:
            time.sleep(0.2)  # line 18: constant backoff


def bounded_but_constant(peer, sock):
    for _ in range(500):
        try:
            return sock.connect(peer)
        except OSError:
            time.sleep(0.2)  # line 26: constant backoff


def hot_hammer(url):
    deadline = time.time() + 10
    while True:  # line 31: bounded (deadline) but no sleep at all
        if time.time() > deadline:
            raise TimeoutError
        try:
            return urllib.request.urlopen(url, timeout=5)
        except OSError:
            continue


def suppressed_constant(url):
    while True:  # kflint: allow(retry-discipline)
        try:
            return urllib.request.urlopen(url, timeout=5)
        except OSError:
            # waived loop; the sleep still carries its own waiver
            time.sleep(0.5)  # kflint: allow(retry-discipline)


def good_deadline_backoff(url):
    deadline = time.monotonic() + 30
    attempt = 0
    while True:  # bounded by the deadline compare; blessed backoff
        try:
            return urllib.request.urlopen(url, timeout=5)
        except OSError:
            if time.monotonic() > deadline:
                raise
            sleep_backoff(attempt)
            attempt += 1


def good_attempt_ladder(sock, peer):
    for i in range(5):  # bounded; computed (growing) sleep
        try:
            return sock.connect(peer)
        except OSError:
            time.sleep(0.5 * (i + 1))


def good_jittered_poll(url):
    while time.time() < 99:  # real while-condition = bounded
        try:
            return urllib.request.urlopen(url, timeout=5)
        except OSError:
            time.sleep(0.2 * (0.5 + random.random()))


def not_a_retry_iterating_targets(channel, runners, stage):
    for runner in runners:  # per-TARGET try/except is not a retry loop
        try:
            channel.send(runner, "update", stage)
        except (TimeoutError, ConnectionError):
            pass
