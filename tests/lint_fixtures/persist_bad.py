"""handle-discipline fixture: persist-plane shapes the rule must catch
(elastic/persist.py fences + the persist_async issue site)."""


def dropped_persist(plane, boundary):
    plane.persist_async(3, boundary)        # line 6: handle dropped
    return boundary


def persist_never_waited(plane, boundary):
    h = plane.persist_async(3, boundary)    # line 11: never waited
    return boundary


def held_across_restore(plane, boundary):
    h = plane.persist_async(3, boundary)    # line 16: not settled before
    st = restore_from_manifest("/ckpt", 0, 2)   # the restore fence
    h.wait()
    return st


def held_across_plane_fence(plane, engine, x):
    h = engine.all_reduce_async(x)          # line 23: straddles the
    plane.persist_fence()                   # plane's own fence
    return h.wait()


def persist_held_across_elastic(plane, peer, state, schedule, params, b):
    h = plane.persist_async(5, b)           # line 29: persist handle
    state, params, stop = elastic_step(     # straddles elastic_step
        peer, state, schedule, params)
    return h.wait(), state, params, stop


def restore_from_manifest(mdir, my_new, new_n):
    return None


def elastic_step(peer, state, schedule, params):
    return state, params, False
