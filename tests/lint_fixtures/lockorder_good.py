"""Compliant locking: one global order (mu before nu) on every path,
and reentrancy where a helper legitimately re-enters.  Must lint clean."""
import threading


class Pipeline:
    def __init__(self):
        self.mu = threading.RLock()
        self.nu = threading.Lock()
        self.items = []

    def forward(self):
        with self.mu:
            with self.nu:
                return list(self.items)

    def backward(self):
        # same order as forward — no inversion
        with self.mu:
            with self.nu:
                self.items.append(0)

    def _locked_len(self):
        with self.mu:
            return len(self.items)

    def report(self):
        # mu is an RLock: re-entry through a helper is legal
        with self.mu:
            return self._locked_len()
