"""Seeded rng-discipline violations (tests/test_det.py pins the line
numbers below — keep edits append-only)."""
import os
import time

import jax
import numpy as np


def split_reuse(key, x):
    # BAD: `key` is consumed by the split but used again — the normal
    # draw duplicates the stream k1/k2 were derived from
    k1, k2 = jax.random.split(key)
    return jax.random.normal(key, x.shape) + k1.sum() + k2.sum()


def split_again(key):
    # BAD: the second split re-consumes the dead key: both splits
    # return identical children
    k1 = jax.random.split(key)[0]
    k2 = jax.random.split(key)[0]
    return k1, k2


def fold_in_entropy(key):
    # BAD: folding wall-clock into the key forks rank-divergent,
    # replay-divergent streams
    return jax.random.fold_in(key, int(time.time()))


def entropy_seed():
    # BAD: the root key must derive from agreed values, not the pid
    return jax.random.PRNGKey(os.getpid())


def entropy_np_seed():
    # BAD: same discipline for numpy generators on replay paths
    return np.random.default_rng(int(time.time_ns()))


@jax.jit
def np_random_in_jit(x):
    # BAD: the draw happens once at trace time and is baked into the
    # compiled artifact
    noise = np.random.rand(4)
    return x + noise


def waived_jitter(key):
    # suppressed: documented local-only jitter stream
    return jax.random.fold_in(key, int(time.monotonic()))  # kflint: allow(rng-discipline)
