"""proto-verify fixture: p2p tag pairing broken — every send tag must
be matched by a recv of the same skeleton, and vice versa."""
import numpy as np


def proto_entry_scatter(engine, chan, me, peers, payload):
    for i, p in enumerate(peers):
        chan.send(p, f"kf.orph.a{i}", payload)
    out = []
    for i, p in enumerate(peers):
        out.append(chan.recv(p, f"kf.orph.c{i}"))
    return out
