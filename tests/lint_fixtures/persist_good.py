"""handle-discipline fixture: compliant persist-plane shapes that must
NOT flag."""


def issue_then_wait(plane, boundary):
    h = plane.persist_async(3, boundary)
    return h.wait()


def commit_returns_handle(plane, step, boundary):
    # the plane's own period-gated commit: escape-by-return — the
    # caller (or the internal tracking + persist_fence) settles it
    return plane.persist_async(step, boundary)


def fence_settles_tracked_writes(plane, boundary):
    # no explicitly-held handle: commit() tracks internally and the
    # boundary fence drains — the canonical train-loop shape
    plane.commit(3, boundary)
    plane.persist_fence()
    return boundary


def wait_then_restore(plane, boundary):
    h = plane.persist_async(3, boundary)
    h.wait()
    st = restore_from_manifest("/ckpt", 0, 2)   # fence AFTER settle
    return st


def windowed_persists(plane, boundary, steps, handles):
    for s in steps:
        handles.append(plane.persist_async(s, boundary))
    return handles


def restore_from_manifest(mdir, my_new, new_n):
    return None
