"""Fixture: seeded shard-spec violations (never imported by the app)."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build():
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2), ("x", "y"))

    def body(a, b):
        return a + b

    good = shard_map(body, mesh=mesh, in_specs=(P("x"), P(None, "y")),
                     out_specs=P("x"))
    bad_axis = shard_map(body, mesh=mesh,
                         in_specs=(P("x"), P("w")),     # VIOLATION: no w
                         out_specs=P("x"))
    dup = shard_map(body, mesh=mesh,
                    in_specs=(P("x", "x"), P(None)),    # VIOLATION: x twice
                    out_specs=P("x"))
    arity = shard_map(body, mesh=mesh,                  # VIOLATION: 1 vs 2
                      in_specs=(P("x"),),
                      out_specs=P("x"))

    def pair(a):
        return a, a

    out_arity = shard_map(pair, mesh=mesh,              # VIOLATION: 3 vs 2
                          in_specs=(P("x"),),
                          out_specs=(P("x"), P("y"), P()))
    ns = NamedSharding(mesh, P("x", "zz"))              # VIOLATION: no zz
    waived = shard_map(body, mesh=mesh, out_specs=P("x"),
                       in_specs=(P("x"), P("qq")))  # kflint: allow(shard-spec)
    return good, bad_axis, dup, arity, out_arity, ns, waived


def vocab_only():
    return P(None, "nope")                              # VIOLATION: unknown
