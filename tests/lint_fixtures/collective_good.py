"""Compliant collective usage: symmetric splits, versioned names, and
names derived from cluster-agreed state.  Must lint clean."""


def symmetric_broadcast(peer, blob):
    # the root/leaf split issues the SAME (op, name) on both sides — the
    # shrink replay-point broadcast idiom
    name = f"boot.v{peer.cluster_version}"
    if peer.rank() == 0:
        peer.channel.broadcast_bytes(blob, peer.cluster.workers, name)
        return blob
    return peer.channel.broadcast_bytes(None, peer.cluster.workers, name)


def versioned_sync(peer, digest):
    return peer.channel.consensus_bytes(
        digest, peer.cluster.workers, name=f"sync.v{peer.cluster_version}"
    )


def another_versioned_sync(peer, digest):
    # same shape as versioned_sync but the names are f-strings, not
    # constants — versioned names never collide as "reuse"
    return peer.channel.consensus_bytes(
        digest, peer.cluster.workers, name=f"sync.v{peer.cluster_version}"
    )


def agreed_gather(peer, blob, digest):
    # a payload-digest name is cluster-agreed state, not local entropy
    return peer.channel.gather_bytes(
        blob, peer.cluster.workers, name=f"snap.{digest}"
    )


def _shared_phase(peer):
    peer.channel.barrier(peer.cluster.workers, name="phase")


def every_rank_announces(peer):
    # the helper is reached unconditionally — fine
    _shared_phase(peer)
