"""Fixture: seeded jit-purity violations (never imported by the app)."""

import time

import jax
import numpy as np


@jax.jit
def bad_step(x):
    v = float(x)                      # VIOLATION: host sync
    print("loss", v)                  # VIOLATION: side effect
    t = time.time()                   # VIOLATION: traces to a constant
    y = np.asarray(x)                 # VIOLATION: device->host copy
    z = x.item()                      # VIOLATION: host sync
    n = int(x.shape[0])               # ok: static under trace
    allowed = x.item()  # kflint: allow(jit-sync)
    return y + z + t + n + v + helper(x) + allowed


def helper(x):
    return x.tolist()                 # VIOLATION: one level deep


def make_step():
    # call-form wrapping must be tracked too
    return jax.jit(_body)


def _body(x):
    x.block_until_ready()             # VIOLATION: call-form jit
    return x


def outer_clean():
    def shared_name(x):
        return x + 1
    return shared_name


def outer_dirty():
    def shared_name(x):
        return float(x.sum())         # VIOLATION: same-named nested def
    return jax.jit(shared_name)
