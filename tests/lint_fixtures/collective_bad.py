"""Seeded collective-consistency violations (tests/test_lint.py pins the
line numbers below — keep edits append-only)."""
import time


def asymmetric_broadcast(peer, blob):
    # BAD: only rank 0 ever issues this collective -> every other rank
    # waits on a rendezvous that never happens
    if peer.rank() == 0:
        peer.channel.broadcast_bytes(blob, peer.cluster.workers, name="boot")


def _announce(peer):
    peer.channel.barrier(peer.cluster.workers, name="announce")


def leader_only_announce(peer):
    # BAD (interprocedural): _announce issues a barrier but is reached
    # only through this rank-conditional call site
    if peer.rank() == 0:
        _announce(peer)


def first_sync(peer, digest):
    return peer.channel.consensus_bytes(
        digest, peer.cluster.workers, name="sync"
    )


def second_sync(peer, digest):
    # BAD: constant rendezvous name reused from first_sync — concurrent
    # paths alias each other's messages
    return peer.channel.consensus_bytes(
        digest, peer.cluster.workers, name="sync"
    )


def stamped_gather(peer, blob):
    # BAD: time.time() diverges across peers, the name never rendezvouses
    return peer.channel.gather_bytes(
        blob, peer.cluster.workers, name=f"snap.{time.time()}"
    )


def waived_probe(peer, blob):
    # suppressed: a deliberately rank-local debug path, documented here
    if peer.rank() == 0:
        peer.channel.gather_bytes(blob, peer.cluster.workers, name="probe")  # kflint: allow(collective-consistency)
