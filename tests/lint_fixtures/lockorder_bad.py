"""Seeded lock-order violations: an AB/BA inversion across methods and
an interprocedural self-deadlock (tests/test_lint.py pins the lines)."""
import threading


class Pipeline:
    def __init__(self):
        self.mu = threading.Lock()
        self.nu = threading.Lock()
        self.items = []

    def forward(self):
        # takes mu then nu ...
        with self.mu:
            with self.nu:
                return list(self.items)

    def backward(self):
        # BAD: ... while this path takes nu then mu (AB/BA inversion —
        # two threads in forward()/backward() deadlock)
        with self.nu:
            with self.mu:
                self.items.append(0)

    def _locked_len(self):
        with self.mu:
            return len(self.items)

    def report(self):
        # BAD (interprocedural): calls a mu-taking helper while holding
        # the non-reentrant mu — guaranteed self-deadlock
        with self.mu:
            return self._locked_len()
