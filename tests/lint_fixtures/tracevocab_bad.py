"""trace-vocab fixture: typo'd / dynamic / missing event kinds."""

from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.timeline import event as ev


def good_mark():
    timeline.event("mark", "boot-done")  # in vocabulary: clean


def typo_kind():
    timeline.event("colective", "engine.all_reduce")  # typo: flagged


def dynamic_kind(k):
    with timeline.span(k, "engine.all_reduce"):  # dynamic: flagged
        pass


def no_kind():
    ev()  # missing kind: flagged


def aliased_typo():
    ev("shrnk", "consensus")  # typo through the alias: flagged


def waived(k):
    timeline.event(k, "escape-hatch")  # kflint: allow(trace-vocab)


class Unrelated:
    def span(self, *a):
        return self

    def event(self, *a):
        return self


def not_the_timeline():
    u = Unrelated()
    u.span("whatever")  # other receiver: NOT flagged
    u.event("whatever")
