"""Order-pinned folds reduction-order must NOT flag (also copied under
``kungfu_tpu/ops/`` by tests/test_det.py)."""


def list_bucket_fold(widths, slabs):
    # lists iterate in construction order — pinned
    parts = []
    off = 0
    for w in widths:
        parts.append(slabs[off:off + w])
        off += w
    return parts


def sorted_set_fold(widths):
    # the canonical-order escape hatch: sorted() pins the fold order
    total = 0.0
    for w in sorted(set(widths)):
        total += w
    return total


def sorted_dict_fold(buckets):
    acc = 0.0
    for name in sorted(buckets.keys()):
        acc += buckets[name]
    return acc


def sum_over_sorted(vals):
    return sum(v * v for v in sorted(set(vals)))


def membership_is_fine(vals, allow):
    # set membership tests are order-insensitive
    return [v for v in vals if v in {"a", "b", "c"} and v not in allow]
