"""ledger-schema fixture: typo'd / dynamic decision-ledger field names."""

from kungfu_tpu.monitor import ledger
from kungfu_tpu.monitor.ledger import lfield as lf, ledger_record, record_decision


def good_reads(rec):
    actor = ledger.lfield(rec, "actor")  # in schema: clean
    return actor, lf(rec, "verdict")  # through the alias: clean


def typo_read(rec):
    return ledger.lfield(rec, "actr")  # typo: flagged


def dynamic_read(rec, k):
    return lf(rec, k)  # dynamic: flagged


def no_name(rec):
    return ledger.lfield(rec)  # missing name: flagged


def good_record():
    return ledger_record(actor="x", knob="k", old=1, new=2)  # clean


def typo_record():
    return ledger_record(actor="x", knbo="k")  # typo'd field: flagged


def splat_record(extra):
    return ledger_record(actor="x", **extra)  # dynamic splat: flagged


def good_decision():
    record_decision("x", "k", 1, 2, evidence={"why": 1})  # clean


def typo_decision():
    record_decision("x", "k", 1, 2, evidnce={})  # typo'd field: flagged


def waived(rec, k):
    return ledger.lfield(rec, k)  # kflint: allow(ledger-schema)


class Unrelated:
    def lfield(self, *a):
        return self

    def ledger_record(self, *a):
        return self


def not_the_ledger():
    u = Unrelated()
    u.lfield("whatever")  # other receiver: NOT flagged
    u.ledger_record(bogus=1)
