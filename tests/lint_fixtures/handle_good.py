"""handle-discipline fixture: compliant shapes that must NOT flag."""


def straight_line(engine, x):
    h = engine.all_reduce_async(x)
    return h.wait()


def both_branches(engine, x, flag):
    h = engine.reduce_scatter_async(x)
    if flag:
        out = h.wait()
    else:
        out = h.wait(timeout=5.0)
    return out


def try_finally(engine, x):
    h = engine.all_gather_async(x)
    try:
        prepare(x)
    finally:
        out = h.wait()
    return out


def escapes_by_return(engine, x):
    # ownership transferred to the caller — their discipline now
    return engine.all_reduce_async(x)


def escapes_into_collection(engine, xs, handles):
    for x in xs:
        handles.append(engine.reduce_scatter_async(x))
    return handles


def escapes_to_helper(engine, x):
    h = engine.all_gather_async(x)
    consume(h)
    return None


def wait_then_resize(engine, peer, x):
    h = engine.all_reduce_async(x)
    out = h.wait()
    peer.resize_cluster(2)  # fence AFTER the settle: fine
    return out


def wait_then_stage_recarve(engine, boundary, peer, x):
    # the pp activation hop settles BEFORE the stage re-carve: fine
    h = engine.send_async(1, x, "pp.act")
    h.wait()
    boundary.recarve(2, peer=peer)
    return boundary


def pipelined_window(engine, xs):
    # the canonical depth-k pipeline: issue nested in an expression
    # flows into the deque — not a tracked bare handle
    from collections import deque

    handles = deque(engine.reduce_scatter_async(x) for x in xs[:2])
    outs = []
    for i, x in enumerate(xs):
        got = handles.popleft().wait()
        if i + 2 < len(xs):
            handles.append(engine.reduce_scatter_async(xs[i + 2]))
        outs.append(got)
    return outs


def with_block_wait_then_resize(engine, peer, span, x):
    # a wait inside a with-block settles the handle — the fence after
    # the block must not flag
    h = engine.all_reduce_async(x)
    with span("collective"):
        out = h.wait()
    peer.resize_cluster(2)
    return out


def prepare(x):
    return x


def consume(h):
    return h.wait()
