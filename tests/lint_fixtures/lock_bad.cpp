// Fixture: seeded lock-discipline violations (never compiled).
#include <mutex>
#include <vector>

class Counter {
  public:
    void good() {
        std::lock_guard<std::mutex> lk(mu_);
        count_ += 1;  // ok: mu_ held
        items_.push_back(count_);
    }

    void good_nested() {
        std::lock_guard<std::mutex> lk(mu_);
        if (count_ > 0) {
            count_ -= 1;  // ok: mu_ held in enclosing scope
        }
    }

    void bad_unlocked() {
        count_ = 0;  // VIOLATION: no lock
        items_.clear();  // VIOLATION: no lock
    }

    void bad_wrong_lock() {
        std::lock_guard<std::mutex> lk(other_mu_);
        ++count_;  // VIOLATION: holds other_mu_, not mu_
    }

    void allowed_single_threaded() {
        count_ = -1;  // kflint: allow(lock-discipline)
    }

    void bad_unlock_window() {
        std::unique_lock<std::mutex> lk(mu_);
        lk.unlock();
        count_ = 7;  // VIOLATION: written in the unlock window
        lk.lock();
        count_ = 8;  // ok: relocked
    }

    void ok_unlock_and_return() {
        std::unique_lock<std::mutex> lk(mu_);
        if (count_ > 0) { lk.unlock(); return; }
        count_ = 9;  // ok: the unlocking branch returned
    }

  private:
    std::mutex mu_;
    std::mutex other_mu_;
    int count_ = 0;                 // guarded_by(mu_)
    std::vector<int> items_;        // guarded_by(mu_)
};
