"""proto-verify fixture: collective ordering divergence — a collective
under a one-sided rank guard, and a bucket loop running against
canonical order (the uniform swap no cross-rank comparison can see)."""
import numpy as np


def proto_entry_diverge(engine, me, grads):
    if me == 0:
        engine.all_reduce(grads, name="kf.ord.g")
    return grads


def proto_entry_buckets(engine, spans, grads):
    for i in range(len(spans)):
        engine.reduce_scatter(grads[i], op="sum",
                              name=f"kf.ord.b{len(spans) - 1 - i}")
    for i in range(len(spans)):
        engine.all_gather(grads[i], name=f"kf.ord.b{len(spans) - 1 - i}")
