"""proto-verify fixture: a clean symmetric protocol — canonical bucket
order, paired tags, send-before-recv mirror, balanced collectives."""
import numpy as np


def proto_entry_buckets(engine, spans, grads):
    for i in range(len(spans)):
        engine.reduce_scatter(grads[i], op="sum", name=f"kf.good.b{i}")
    for i in range(len(spans)):
        engine.all_gather(grads[i], name=f"kf.good.b{i}")


def proto_entry_ring(chan, me, world, blob):
    pred = (me - 1) % world
    succ = (me + 1) % world
    chan.send(pred, f"kf.good.ring.{me}", blob)
    return chan.recv(succ, f"kf.good.ring.{succ}")


def proto_entry_guarded(engine, me, grads):
    if me == 0:
        engine.all_reduce(grads, name="kf.good.g")
    else:
        engine.all_reduce(grads, name="kf.good.g")
    return grads


def proto_entry_exchange(engine, me, peers, payload):
    hs = []
    for i, p in enumerate(peers):
        hs.append(engine.send_async(p, payload, f"kf.good.x{i}"))
        engine.recv_async(p, f"kf.good.x{i}")
    for h in hs:
        h.wait()
