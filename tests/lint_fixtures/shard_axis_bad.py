"""Fixture: seeded shard-axis violations (never imported by the app)."""

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

MESH_AXES = ("x", "y")


def build():
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2), MESH_AXES)

    def body(a):
        s = jax.lax.psum(a, "x")            # ok: bound here
        t = jax.lax.psum(a, "z")            # VIOLATION: no mesh declares z
        u = jax.lax.pmean(a, ("x", "y"))    # ok: tuple, both bound
        v = jax.lax.axis_index("y")         # ok
        w = jax.lax.psum(a, "q")  # kflint: allow(shard-axis) — doc'd waiver
        return s + t + u + v + w

    return shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))


def helper(a):
    # "y" IS a declared axis (build's mesh) but the only caller runs on
    # the 1-D sub-mesh ("x",): flagged via the environment layer
    return jax.lax.psum(a, "y")             # VIOLATION: not bound in ctx {x}


def sub():
    mesh1 = Mesh(np.array(jax.devices()[:2]), ("x",))

    def body1(a):
        return helper(a)

    return shard_map(body1, mesh=mesh1, in_specs=(P("x"),), out_specs=P("x"))


def dyn(a, axis):
    return jax.lax.psum(a, axis)            # ok: dynamic, callers carry it


def default_bad(a, axis="zz"):              # VIOLATION: default undeclared
    return jax.lax.psum(a, axis)
