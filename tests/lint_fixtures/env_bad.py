"""Fixture: seeded env-contract violations (never imported by the app)."""

import os

registered = os.environ.get("KF_SELF_SPEC")            # ok: in registry
rogue = os.environ.get("KF_TOTALLY_UNREGISTERED_KNOB")  # VIOLATION
allowed = os.environ.get("KF_WAIVED_KNOB")  # kflint: allow(env-contract)
