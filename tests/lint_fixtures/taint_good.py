"""Sanctioned flows replay-taint must NOT flag (tests/test_det.py runs
the rule over this file and asserts zero findings)."""
import hashlib
import json
import time


def agreed_digest(peer, workers, payload):
    # the digest derives from the payload every rank already agrees on
    digest = hashlib.blake2b(payload, digest_size=8).hexdigest()
    peer.channel.barrier(workers, name=f"kf.slice.{digest}")


def round_tripped(peer, workers, blob):
    # an agreement op's RESULT is the agreed value — taint dies there
    agreed = peer.channel.consensus_bytes(blob, workers, name="agree")
    peer.channel.consensus_bytes(agreed, workers, name="install")


def agreed_metadata(peer, workers, step, cluster_version):
    # (step, cluster_version) are agreed values, not entropy
    meta = {"step": int(step), "v": int(cluster_version)}
    peer.channel.consensus_bytes(json.dumps(meta).encode(), workers,
                                 name=f"kf.persist.agree.v{cluster_version}")


def sorted_set_tag(peer, workers, ranks):
    # sorted() pins the order: the canonical-order escape hatch
    survivors = ",".join(str(r) for r in sorted(set(ranks)))
    peer.channel.barrier(workers, name=f"kf.shrink.{survivors}")


def local_gauge_only(peer, workers, blob):
    # wall-clock feeding a LOCAL gauge is sanctioned — it never reaches
    # a replay-critical sink
    t0 = time.monotonic()
    peer.channel.broadcast_bytes(blob, workers, name="steady")
    return time.monotonic() - t0
