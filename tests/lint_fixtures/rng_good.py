"""Sanctioned PRNG usage rng-discipline must NOT flag."""
import jax
import numpy as np


def threaded_split(key, x):
    # the canonical idiom: the split REBINDS key, so nothing is reused
    key, sub = jax.random.split(key)
    noise = jax.random.normal(sub, x.shape)
    key, sub = jax.random.split(key)
    return noise + jax.random.normal(sub, x.shape)


def fanout_split(key, n):
    # consuming fan-out: key is rebound by the same assignment
    key, *subs = jax.random.split(key, n + 1)
    return key, subs


def agreed_fold_in(key, step, layer):
    # folding agreed values produces identical streams on every rank
    # and every replay
    k = jax.random.fold_in(key, step)
    return jax.random.fold_in(k, layer)


def agreed_seed(cluster_version, step):
    # seed material from agreed state
    return jax.random.PRNGKey(cluster_version * 1_000_003 + step)


def seeded_numpy(seed):
    # a threaded seed is fine — determinism is the caller's contract
    return np.random.default_rng(seed)


def loop_threading(key, xs):
    # rebinding inside the loop keeps the chain linear
    out = []
    for x in xs:
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, x.shape))
    return out
