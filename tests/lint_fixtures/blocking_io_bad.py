"""Fixture: seeded blocking-io violations (never imported by the app)."""

import queue
import socket
import threading
import urllib.request

work_q: "queue.Queue" = queue.Queue(maxsize=4)
free_q: "queue.Queue" = queue.Queue()  # unbounded: put() never blocks


def worker():
    while True:
        item = work_q.get()               # VIOLATION: no timeout
        ok = work_q.get(timeout=1.0)      # ok
        allowed = work_q.get()  # kflint: allow(blocking-io)
        free_q.put(item)                  # ok: unbounded queue
        work_q.put(ok)                    # VIOLATION: bounded, no timeout
        del allowed


def fetch(url):
    return urllib.request.urlopen(url)    # VIOLATION: no timeout


def fetch_bounded(url):
    return urllib.request.urlopen(url, timeout=3.0)  # ok


def serve(listen_sock: socket.socket):
    conn, _ = listen_sock.accept()        # VIOLATION: no deadline
    data = conn.recv(4096)                # VIOLATION: no settimeout
    return data


def positional_forms():
    a = work_q.get(False)                 # ok: non-blocking positional
    b = work_q.get(True, 5.0)             # ok: positional timeout
    c = work_q.get(True)                  # VIOLATION: blocks forever
    work_q.put(a, False)                  # ok: non-blocking positional
    work_q.put(b, True, 2.0)              # ok: positional timeout
    return c


threading.Thread(target=worker, daemon=True)
