"""Adaptation payoff: a strategy swap must RECOVER throughput, not just
happen (round-3 VERDICT item 5; the reference runs its adaptation bench
in CI, ``.github/workflows/ci.yaml:54`` + ``benchmarks/adaptation``).

Scenario: a 3-peer cluster on the STAR strategy (all traffic hubs through
rank 0).  The 0↔1 link degrades (5 ms injected per send — a congested
cross-rack link).  The full, unforced loop must then close end-to-end:

  real window drop → interference suspicion → majority vote →
  latency probe → MST avoiding the slow edge → fenced set_tree swap →
  measured step time recovers.

The Python wire path is used (``KF_NATIVE_ENGINE=0``) so the per-link
delay can be injected at the channel boundary; the adaptation logic
above the channel is identical for both backends.
"""

import time

import numpy as np
import pytest

from kungfu_tpu.monitor.adaptive import AdaptiveStrategyDriver
from kungfu_tpu.plan import Cluster, PeerList, Strategy

from tests._util import run_all

DELAY_S = 0.03  # per-send injected latency; must dominate 1-core scheduling noise
PORTS = "127.0.0.1:27401,127.0.0.1:27402,127.0.0.1:27403"


class TestAdaptationPayoff:
    @pytest.fixture
    def peers(self, monkeypatch):
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        workers = PeerList.parse(PORTS)
        runners = PeerList.parse("127.0.0.1:38088")
        cluster = Cluster(runners, workers)
        ps = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
        for p in ps:
            p.config.strategy = Strategy.STAR
            p.start()
        yield ps
        for p in ps:
            p.close()


    @staticmethod
    def _throttle_link(peer, other_spec: str):
        """Inject DELAY_S into every send and ping from ``peer`` toward
        ``other_spec`` — a slow link as seen from this endpoint."""
        ch = peer.channel
        orig_send, orig_ping = ch.send, ch.ping

        def slow_send(target, name, payload, *a, **kw):
            if str(target) == other_spec:
                time.sleep(DELAY_S)
            return orig_send(target, name, payload, *a, **kw)

        def slow_ping(target, *a, **kw):
            if str(target) == other_spec:
                time.sleep(DELAY_S)
            return orig_ping(target, *a, **kw)

        ch.send, ch.ping = slow_send, slow_ping
        return (ch, orig_send, orig_ping)

    def test_mst_swap_recovers_throughput(self, peers):
        workers = [str(w) for w in peers[0].cluster.workers]
        drivers = [
            AdaptiveStrategyDriver(
                p, check_every=1, min_steps_between_swaps=1, use_mst=True
            )
            for p in peers
        ]
        data = np.ones(200_000, np.float32)

        def step(p, d):
            t0 = time.perf_counter()
            out = p.engine().all_reduce(data, op="sum")
            dt = time.perf_counter() - t0
            swapped = d.step()
            return out, dt, swapped

        def run_steps(n):
            times, swaps = [], []
            for _ in range(n):
                outs = run_all(
                    [lambda p=p, d=d: step(p, d) for p, d in zip(peers, drivers)]
                )
                for o, _, _ in outs:
                    np.testing.assert_allclose(o, data * 3)
                times.append(max(dt for _, dt, _ in outs))
                flags = {s for _, _, s in outs}
                assert len(flags) == 1  # lockstep swap decision
                swaps.append(flags.pop())
            return times, swaps

        # healthy phase: establish each peer's best-throughput window.
        # A spurious swap needs 2 consecutive degraded windows + majority
        # on an unthrottled cluster: retry once — a load spike on the CI
        # box passes the second attempt, while a driver regression that
        # always votes interference fails BOTH attempts (and the test)
        for attempt in range(2):
            healthy, swaps = run_steps(3)
            if not any(swaps):
                break
        else:
            pytest.fail(
                "interference voted on a healthy cluster in two separate "
                "3-step phases — trigger-happy driver, not CI-box noise"
            )

        # degrade the 0<->1 link on both endpoints
        restores = [
            self._throttle_link(peers[0], workers[1]),
            self._throttle_link(peers[1], workers[0]),
        ]
        try:
            throttled = []
            swapped = False
            for _ in range(8):
                t, s = run_steps(1)
                # the allreduce of a swap step still ran on the throttled
                # topology (the driver swaps AFTER the collective)
                throttled += t
                if s[0]:
                    swapped = True
                    break
            assert swapped, "interference never triggered an MST swap"

            recovered, _ = run_steps(5)
            # medians: single steps jitter heavily on a 1-core CI box
            t_pre = float(np.median(throttled))
            t_post = float(np.median(recovered))
            assert t_post < t_pre * 0.6, (
                f"no payoff: throttled {throttled} vs post-swap {recovered}"
            )
        finally:
            for ch, s, pg in restores:
                ch.send, ch.ping = s, pg
