"""Benchmark-harness smoke tests (the reference runs its benches in CI:
ci.yaml adaptation bench step, monitor bench)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(script, *args, timeout=300):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", script), "--quick", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_bus_bandwidth_formula():
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from allreduce import bus_bandwidth

    assert bus_bandwidth(1 << 30, 2, 1.0) == pytest.approx(1.0)
    assert bus_bandwidth(1 << 30, 4, 0.5) == pytest.approx(3.0)


@pytest.mark.slow
class TestHarnesses:
    def test_allreduce_host(self):
        out = run_bench("allreduce.py", "--backend", "host", "--np", "2")
        assert out["metric"] == "allreduce_bus_bandwidth"
        assert out["value"] > 0

    def test_allreduce_device(self):
        out = run_bench("allreduce.py", "--cpu-mesh", "4")
        assert out["np"] == 4
        assert out["value"] > 0

    def test_system_transformer(self):
        out = run_bench("system.py", "--model", "transformer",
                        "--optimizer", "sync-sgd", "--cpu-mesh", "2")
        assert out["value"] > 0
        assert out["final_loss"] > 0

    def test_adaptation(self):
        out = run_bench("adaptation.py", "--cpu-mesh", "4")
        assert out["metric"] == "resize_transition_latency"
        assert len(out["transitions"]) >= 2

    def test_system_vgg(self):
        out = run_bench("system.py", "--model", "vgg16",
                        "--optimizer", "sync-sgd", "--cpu-mesh", "2")
        assert out["metric"] == "vgg16_sync-sgd_throughput"
        assert out["value"] > 0 and out["unit"] == "images/sec"

    def test_system_bert_sma(self):
        """BASELINE config 3: BERT-base-shaped + SynchronousAveraging."""
        out = run_bench("system.py", "--model", "bert", "--optimizer", "sma",
                        "--cpu-mesh", "2")
        assert out["metric"] == "bert_sma_throughput"
        assert out["value"] > 0 and out["unit"] == "sequences/sec"

    def test_gossip(self):
        """BASELINE config 4: PairAveraging gossip over the p2p store."""
        out = run_bench("gossip.py", "--np", "2", "--model", "slp-mnist",
                        "--steps", "3", "--warmup", "1",
                        "--base-port", "28700")
        assert out["metric"] == "pair_averaging_gossip_steps_per_sec"
        assert out["value"] > 0 and out["np"] == 2
