"""Benchmark-harness smoke tests (the reference runs its benches in CI:
ci.yaml adaptation bench step, monitor bench)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(script, *args, timeout=300, subdir="benchmarks"):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, subdir, script), "--quick", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_bus_bandwidth_formula():
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from allreduce import bus_bandwidth

    assert bus_bandwidth(1 << 30, 2, 1.0) == pytest.approx(1.0)
    assert bus_bandwidth(1 << 30, 4, 0.5) == pytest.approx(3.0)


@pytest.mark.slow
class TestHarnesses:
    def test_allreduce_host(self):
        out = run_bench("allreduce.py", "--backend", "host", "--np", "2")
        assert out["metric"] == "allreduce_bus_bandwidth"
        assert out["value"] > 0

    def test_allreduce_device(self):
        out = run_bench("allreduce.py", "--cpu-mesh", "4")
        assert out["np"] == 4
        assert out["value"] > 0

    def test_system_transformer(self):
        out = run_bench("system.py", "--model", "transformer",
                        "--optimizer", "sync-sgd", "--cpu-mesh", "2")
        assert out["value"] > 0
        assert out["final_loss"] > 0

    def test_adaptation(self):
        out = run_bench("adaptation.py", "--cpu-mesh", "4")
        assert out["metric"] == "resize_transition_latency"
        assert len(out["transitions"]) >= 2

    def test_system_vgg(self):
        out = run_bench("system.py", "--model", "vgg16",
                        "--optimizer", "sync-sgd", "--cpu-mesh", "2")
        assert out["metric"] == "vgg16_sync-sgd_throughput"
        assert out["value"] > 0 and out["unit"] == "images/sec"

    def test_system_bert_sma(self):
        """BASELINE config 3: BERT-base-shaped + SynchronousAveraging."""
        out = run_bench("system.py", "--model", "bert", "--optimizer", "sma",
                        "--cpu-mesh", "2")
        assert out["metric"] == "bert_sma_throughput"
        assert out["value"] > 0 and out["unit"] == "sequences/sec"

    def test_scaling_sweep(self):
        """The scaling-ladder harness (reference benchmark_kungfu_scaling
        analog): per-size throughput + efficiency in one JSON."""
        # outer timeout > sum of per-size inner timeouts, so two rungs
        # individually within budget cannot kill the test
        out = run_bench("scaling.py", "--sizes", "1,2", "--quick",
                        "--timeout", "200", timeout=520)
        assert out["metric"] == "transformer_sync-sgd_scaling"
        assert set(out["throughput_by_np"]) == {"1", "2"}
        assert out["throughput_by_np"]["1"] > 0
        assert out["baseline_np"] == 1
        assert out["overhead_retention_vs_np1"]["1"] == 1.0

    def test_system_zero1(self):
        """Weight-update sharding through the throughput harness."""
        out = run_bench("system.py", "--model", "transformer",
                        "--optimizer", "zero1", "--cpu-mesh", "2")
        assert out["metric"] == "transformer_zero1_throughput"
        assert out["value"] > 0 and out["final_loss"] > 0

    def test_gossip(self):
        """BASELINE config 4: PairAveraging gossip over the p2p store."""
        out = run_bench("gossip.py", "--np", "2", "--model", "slp-mnist",
                        "--steps", "3", "--warmup", "1",
                        "--base-port", "28700")
        assert out["metric"] == "pair_averaging_gossip_steps_per_sec"
        assert out["value"] > 0 and out["np"] == 2


class TestMeasureGroup:
    """bench.py's interleaved chained-K timing harness (the relay-burst
    defense every recorded TPU ratio rides on)."""

    @staticmethod
    def _measure_group():
        sys.path.insert(0, REPO)
        from bench import measure_group

        return measure_group

    def test_times_every_contestant(self):
        measure_group = self._measure_group()
        import jax.numpy as jnp

        t = measure_group(
            {"a": lambda c: c * 1.0001, "b": lambda c: c * 1.0002},
            jnp.ones((8,)), k_lo=1, k_hi=3, rounds=1,
        )
        assert set(t) == {"a", "b"}
        assert all(v > 0 for v in t.values())

    def test_on_error_skip_maps_to_none(self):
        measure_group = self._measure_group()
        import jax.numpy as jnp

        def boom(c):
            raise RuntimeError("does not lower")

        t = measure_group(
            {"ok": lambda c: c * 1.0001, "bad": boom},
            jnp.ones((8,)), k_lo=1, k_hi=2, rounds=1, on_error="skip",
        )
        assert t["bad"] is None and t["ok"] > 0

    def test_on_error_raise_propagates(self):
        measure_group = self._measure_group()
        import jax.numpy as jnp

        def boom(c):
            raise RuntimeError("does not lower")

        with pytest.raises(RuntimeError):
            measure_group({"bad": boom}, jnp.ones((8,)), k_lo=1, k_hi=2)

    def test_respan_grows_fast_contestants(self, capsys):
        """A contestant whose K-separation is below target_sep gets its
        hi program rebuilt with a bigger span (the jitter defense every
        recorded TPU number now rides on)."""
        measure_group = self._measure_group()
        import jax.numpy as jnp

        t = measure_group(
            {"fast": lambda c: c * 1.0001},
            jnp.ones((8,)), k_lo=1, k_hi=3, rounds=2,
            target_sep=0.005, max_rounds=4,
        )
        assert t["fast"] > 0
        assert "re-span" in capsys.readouterr().err

    def test_rounds_1_skips_respan_and_settle(self, capsys):
        measure_group = self._measure_group()
        import jax.numpy as jnp

        t = measure_group(
            {"fast": lambda c: c * 1.0001},
            jnp.ones((8,)), k_lo=1, k_hi=3, rounds=1, target_sep=10.0,
        )
        assert t["fast"] > 0
        err = capsys.readouterr().err
        assert "re-span" not in err and "settled" not in err


@pytest.mark.slow
class TestBenchPayloads:
    def test_lm_quick(self):
        """bench.py --lm: the kernels-in-anger payload, CPU/interpret."""
        out = run_bench("bench.py", "--payload", "lm", "--cpu",
                        "--steps", "2", timeout=420, subdir="")
        assert out["metric"] == "gpt_small_sync_sgd_tokens_per_sec_per_chip"
        assert out["value"] > 0 and out["unit"] == "tokens/sec"
        # vs_baseline is t_xla / t_pallas (the kernel path's speedup; <1
        # expected in CPU interpret mode), not a reference baseline
        assert out["vs_baseline"] > 0
        assert out["final_loss"] is not None

    def test_resnet_quick(self):
        """The driver's headline payload: framework-path ResNet training.
        (--quick pins batch/img/steps itself, so no --steps here — the
        payload would ignore it.)"""
        out = run_bench("bench.py", "--payload", "resnet", "--cpu",
                        timeout=420, subdir="")
        assert out["metric"] == "resnet50_sync_sgd_images_per_sec_per_chip"
        assert out["value"] > 0 and out["unit"] == "images/sec"
        assert out["final_loss"] is not None
        assert "dp_train_step" in out["framework_path"]

    def test_allreduce(self):
        """Under pytest the conftest's XLA_FLAGS leak an 8-device virtual
        CPU platform into the subprocess (psum path); standalone it sees
        one device (read+write floor).  Both are valid payload branches."""
        out = run_bench("bench.py", "--payload", "allreduce", "--cpu",
                        subdir="")
        assert out["metric"] == "allreduce_bus_bandwidth"
        assert out["value"] > 0 and out["n_devices"] in (1, 8)


class TestZeroPayload:
    def test_zero_rows_and_comm_claim(self):
        """bench.py --zero on the CPU-mesh harness: all four rows
        present, and the measured ZeRO-2 wire bytes hold the <=55%
        claim against the ZeRO-1 all-reduce path."""
        out = run_bench("bench.py", "--payload", "zero", "--cpu-mesh", "4",
                        subdir="")
        assert out["metric"] == "zero2_traced_comm_bytes_vs_zero1"
        assert 0 < out["value"] <= 0.55
        rows = out["rows"]
        assert set(rows) == {"bare", "zero1", "zero2", "zero3"}
        # the bare baseline all-reduces (psum), zero2 reduce-scatters
        assert "psum" in rows["bare"]["traced_comm_bytes_per_rank"]
        assert "reduce_scatter" in rows["zero2"]["traced_comm_bytes_per_rank"]
        assert "all_gather" in rows["zero3"]["traced_comm_bytes_per_rank"]
        # replicated optimizer state is ~n x the sharded per-rank shard
        n = out["n_devices"]
        assert rows["bare"]["opt_state_bytes_per_rank"] > (
            (n - 1) * rows["zero2"]["opt_state_bytes_per_rank"])
        for r in rows.values():
            assert r["step_ms"] is None or r["step_ms"] > 0
