"""Device-plane collective tests on the virtual 8-device CPU mesh.

Numeric cross-check against numpy — the analog of the reference's
fake-trainer integration matrix (scripts/tests/run-integration-tests.sh
sweeping np x strategies) and tests/python/integration/test_operators.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kungfu_tpu.comm import Communicator
from kungfu_tpu.plan import Cluster, HostList
from kungfu_tpu.utils.jaxcompat import shard_map


def make_comm(local_size=None):
    return Communicator(local_size=local_size)


N = 8


@pytest.fixture(scope="module")
def comm():
    assert len(jax.devices()) == N, "conftest must force 8 CPU devices"
    return make_comm()


def stacked(shape=(5,), dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.uniform(-2, 2, size=(N,) + shape).astype(dtype)


class TestAllReduce:
    @pytest.mark.parametrize("shape", [(1,), (5,), (3, 4), (2, 3, 5)])
    def test_sum(self, comm, shape):
        x = stacked(shape)
        out = np.asarray(comm.all_reduce(x))
        want = np.broadcast_to(x.sum(0), x.shape)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    @pytest.mark.parametrize("op,npf", [("min", np.min), ("max", np.max)])
    def test_minmax(self, comm, op, npf):
        x = stacked((7,))
        out = np.asarray(comm.all_reduce(x, op=op))
        want = np.broadcast_to(npf(x, axis=0), x.shape)
        np.testing.assert_allclose(out, want)

    def test_mean(self, comm):
        x = stacked((4,))
        out = np.asarray(comm.all_reduce(x, op="mean"))
        np.testing.assert_allclose(out, np.broadcast_to(x.mean(0), x.shape), rtol=1e-5)

    def test_prod(self, comm):
        x = stacked((3,))
        out = np.asarray(comm.all_reduce(x, op="prod"))
        np.testing.assert_allclose(out, np.broadcast_to(np.prod(x, 0), x.shape), rtol=1e-4)

    def test_int_dtype(self, comm):
        x = np.arange(N * 3, dtype=np.int32).reshape(N, 3)
        out = np.asarray(comm.all_reduce(x))
        np.testing.assert_array_equal(out, np.broadcast_to(x.sum(0), x.shape))

    def test_pytree(self, comm):
        tree = {"a": stacked((2,)), "b": [stacked((3,), seed=1)]}
        out = comm.all_reduce(tree)
        np.testing.assert_allclose(np.asarray(out["a"]), np.broadcast_to(tree["a"].sum(0), (N, 2)), rtol=1e-5)

    def test_bad_leading_axis(self, comm):
        with pytest.raises(ValueError):
            comm.all_reduce(np.ones((3, 2), np.float32))

    def test_bad_op(self, comm):
        with pytest.raises(ValueError):
            comm.all_reduce(stacked(), op="xor")


class TestBroadcastGather:
    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_broadcast(self, comm, root):
        x = stacked((4,))
        out = np.asarray(comm.broadcast(x, root=root))
        np.testing.assert_allclose(out, np.broadcast_to(x[root], x.shape), rtol=1e-6)

    def test_all_gather(self, comm):
        x = stacked((3,))
        out = np.asarray(comm.all_gather(x))
        assert out.shape == (N, N, 3)
        for i in range(N):
            np.testing.assert_allclose(out[i], x, rtol=1e-6)


class TestHierarchical:
    @pytest.fixture(scope="class")
    def hcomm(self):
        # 2 logical hosts x 4 local devices
        return make_comm(local_size=4)

    def test_shape(self, hcomm):
        assert hcomm.num_hosts == 2
        assert hcomm.local_size == 4

    def test_local_all_reduce(self, hcomm):
        x = stacked((2,))
        out = np.asarray(hcomm.local_all_reduce(x))
        want = np.concatenate(
            [np.broadcast_to(x[:4].sum(0), (4, 2)), np.broadcast_to(x[4:].sum(0), (4, 2))]
        )
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_cross_all_reduce(self, hcomm):
        x = stacked((2,))
        out = np.asarray(hcomm.cross_all_reduce(x))
        want = np.concatenate([x[:4] + x[4:], x[:4] + x[4:]])
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_local_broadcast(self, hcomm):
        x = stacked((2,))
        out = np.asarray(hcomm.local_broadcast(x))
        want = np.concatenate(
            [np.broadcast_to(x[0], (4, 2)), np.broadcast_to(x[4], (4, 2))]
        )
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_hierarchy_composes_to_global(self, hcomm):
        """local-reduce -> cross-reduce -> local-broadcast == global allreduce
        (the reference's hierarchical NCCL scheme, gpu/collective.cpp:132-155)."""
        x = stacked((3,))
        step1 = hcomm.local_all_reduce(x)
        step2 = hcomm.cross_all_reduce(step1)
        out = np.asarray(hcomm.local_broadcast(step2))
        np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)


class TestSyncPrimitives:
    def test_barrier(self, comm):
        comm.barrier()  # must not deadlock/throw

    def test_consensus_true(self, comm):
        same = np.broadcast_to(np.arange(4, dtype=np.int32), (N, 4)).copy()
        assert comm.consensus(same)

    def test_consensus_false(self, comm):
        diff = np.zeros((N, 4), np.int32)
        diff[3, 2] = 1
        assert not comm.consensus(diff)

    def test_consensus_bytes_agree(self, comm):
        assert comm.consensus_bytes([b"cluster-digest"] * N)

    def test_consensus_bytes_disagree(self, comm):
        digests = [b"cluster-digest"] * N
        digests[5] = b"other-digest!!"
        assert not comm.consensus_bytes(digests)

    def test_consensus_bytes_length_mismatch(self, comm):
        # same prefix, different lengths — padding must not mask this
        digests = [b"abc"] * N
        digests[2] = b"abc\0"
        assert not comm.consensus_bytes(digests)

    def test_consensus_bytes_rejects_single(self, comm):
        # a lone local byte string is a tautology, not consensus
        with pytest.raises(TypeError):
            comm.consensus_bytes(b"digest")
        with pytest.raises(ValueError):
            comm.consensus_bytes([b"digest"] * (N - 1))


class TestRootValidSemantics:
    """Reference Reduce leaves non-root buffers untouched
    (session.go:157-165); gather's divergence is deliberate + documented."""

    def test_reduce_root_valid(self, comm):
        x = stacked((4,))
        out = np.asarray(comm.reduce(x, root=3))
        np.testing.assert_allclose(out[3], x.sum(0), rtol=1e-5)
        for i in range(N):
            if i != 3:
                np.testing.assert_allclose(out[i], x[i], rtol=1e-6)

    @pytest.mark.parametrize("op", ["min", "max", "mean", "prod"])
    def test_reduce_ops_root_valid(self, comm, op):
        x = stacked((3,), seed=4)
        out = np.asarray(comm.reduce(x, root=0, op=op))
        want = {
            "min": x.min(0), "max": x.max(0),
            "mean": x.mean(0), "prod": np.prod(x, 0),
        }[op]
        np.testing.assert_allclose(out[0], want, rtol=1e-4)
        np.testing.assert_allclose(out[1], x[1], rtol=1e-6)

    def test_gather_is_allgather(self, comm):
        x = stacked((2,))
        out = np.asarray(comm.gather(x))
        for i in range(N):
            np.testing.assert_allclose(out[i], x, rtol=1e-6)


class TestMeshEpochResize:
    """Elastic resize touching the device plane (VERDICT round 1 weak #5):
    a new Communicator epoch over a different device subset must produce
    correct collectives, and Peer.communicator() must rebuild per
    version."""

    def test_new_epoch_smaller_world(self):
        devs = jax.devices()
        c8 = Communicator(devices=devs, local_size=4, version=0)
        c4 = Communicator(devices=devs[:4], local_size=2, version=1)
        x8 = stacked((3,))
        x4 = stacked((3,))[:4]
        np.testing.assert_allclose(
            np.asarray(c8.all_reduce(x8))[0], x8.sum(0), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(c4.all_reduce(x4))[0], x4.sum(0), rtol=1e-5
        )
        assert c4.size == 4 and c4.num_hosts == 2 and c4.local_size == 2
        # hierarchical semantics follow the NEW epoch's mesh
        out = np.asarray(c4.cross_all_reduce(x4))
        want = x4.reshape(2, 2, 3).sum(0)  # reduce over host axis
        np.testing.assert_allclose(out.reshape(2, 2, 3)[0], want, rtol=1e-5)

    def test_resync_parameters_runtime_replication(self):
        """Device-plane state re-sync (round-3 VERDICT item 5): on a
        single-controller mesh, resync replicates every leaf onto the NEW
        epoch by runtime transfer — values exact, placement replicated on
        the communicator's mesh — and survives a shrink + regrow."""
        from kungfu_tpu.initializer import resync_parameters

        devs = jax.devices()
        rng = np.random.default_rng(3)
        params = {
            "w": jnp.asarray(rng.standard_normal((17, 5)), jnp.float32),
            "b": jnp.asarray(rng.standard_normal(5), jnp.float32),
        }
        want = {k: np.asarray(v) for k, v in params.items()}
        for n in (4, 8, 2):
            comm = Communicator(devices=devs[:n], local_size=n)
            params = resync_parameters(params, comm=comm)
            for k, v in params.items():
                np.testing.assert_array_equal(np.asarray(v), want[k])
                assert v.sharding.mesh.devices.size == n
                assert v.sharding.is_fully_replicated

    def test_resync_parameters_no_mesh_falls_back(self):
        from kungfu_tpu.initializer import resync_parameters
        from kungfu_tpu.peer import Peer

        p = Peer()  # single-process config: no channel, size 1
        p.start()
        try:
            params = {"w": jnp.arange(4.0)}
            out = resync_parameters(params, peer=p)
            np.testing.assert_array_equal(np.asarray(out["w"]),
                                          np.arange(4.0))
        finally:
            p.close()

    def test_peer_rebuilds_communicator_on_resize(self):
        from kungfu_tpu.peer import Peer

        p = Peer()  # single-process config
        p.start()
        try:
            c0 = p.communicator()
            assert c0.version == p.cluster_version
            # simulate an applied membership change
            p.cluster_version += 1
            c1 = p.communicator()
            assert c1 is not c0 and c1.version == p.cluster_version
            x = stacked((2,))
            np.testing.assert_allclose(
                np.asarray(c1.all_reduce(x))[0], x.sum(0), rtol=1e-5
            )
        finally:
            p.close()


class TestGroupFused:
    def test_group_all_reduce_matches_individual(self, comm):
        tensors = [stacked((4,)), stacked((2, 3), seed=1), stacked((1,), seed=2)]
        fused = comm.group_all_reduce(tensors, fuse=True)
        plain = comm.group_all_reduce(tensors, fuse=False)
        for f, p in zip(fused, plain):
            np.testing.assert_allclose(np.asarray(f), np.asarray(p), rtol=1e-5)

    def test_mixed_dtypes(self, comm):
        tensors = [stacked((4,)), stacked((3,), seed=1).astype(np.float16)]
        out = comm.group_all_reduce(tensors, fuse=True)
        assert np.asarray(out[1]).dtype == np.float16


class TestInJitOps:
    """kungfu_tpu.ops used inside user shard_map code — the hot path."""

    def test_ops_inside_shard_map(self, comm):
        from jax.sharding import PartitionSpec as P

        from kungfu_tpu import ops

        x = stacked((4,))

        def step(v):
            s = ops.all_reduce(v, axis=comm.axis)
            r = ops.peer_rank(comm.axis)
            return s + 0 * r  # rank used to prove it traces

        f = jax.jit(
            shard_map(
                step, mesh=comm.mesh, in_specs=P(comm.axis), out_specs=P(comm.axis)
            )
        )
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)

    def test_broadcast_op(self, comm):
        from jax.sharding import PartitionSpec as P

        from kungfu_tpu import ops

        x = stacked((4,))
        f = jax.jit(
            shard_map(
                lambda v: ops.broadcast(v, axis=comm.axis, root=2),
                mesh=comm.mesh, in_specs=P(comm.axis), out_specs=P(comm.axis),
            )
        )
        np.testing.assert_allclose(np.asarray(f(x)), np.broadcast_to(x[2], x.shape), rtol=1e-6)


class TestFuse:
    def test_roundtrip(self):
        from kungfu_tpu.ops import defuse, fuse

        tree = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3), "b": jnp.ones((4,), jnp.float32)}
        buf, spec = fuse(tree)
        assert buf.shape == (10,)
        out = defuse(buf, spec)
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(tree["w"]))
        np.testing.assert_allclose(np.asarray(out["b"]), np.asarray(tree["b"]))

    def test_batch_axes(self):
        from kungfu_tpu.ops import defuse, fuse

        tree = [jnp.ones((N, 2, 3)), jnp.zeros((N, 5))]
        buf, spec = fuse(tree, batch_axes=1)
        assert buf.shape == (N, 11)
        out = defuse(buf, spec, batch_axes=1)
        assert out[0].shape == (N, 2, 3)


class TestBroadcastValue:
    def test_broadcast_value_roots_on_slot(self):
        """broadcast_value sends ONE host row (no stacked (n, ...) input)
        and returns the root slot's value on every process."""
        from kungfu_tpu.comm.device import Communicator

        devs = jax.devices()
        comm = Communicator(devices=devs[:4], local_size=2)
        v = np.arange(6, dtype=np.float32)
        # single-controller: every slot's "own" value is the same passed
        # array, so any root returns it — exactness is the contract
        for root in (0, 3):
            out = comm.broadcast_value(v, root_slot=root)
            np.testing.assert_array_equal(out, v)
        with pytest.raises(ValueError):
            comm.broadcast_value(v, root_slot=4)

    def test_first_slot_of_process(self):
        from kungfu_tpu.comm.device import Communicator

        devs = jax.devices()
        comm = Communicator(devices=devs[:4], local_size=2)
        # single-controller CPU world: all devices belong to process 0
        assert comm.first_slot_of_process(0) == 0
        with pytest.raises(ValueError):
            comm.first_slot_of_process(99)


class TestReduceScatterDevice:
    """Communicator.reduce_scatter / all_gather_shard — the device-plane
    ZeRO collective pair (stacked eager convention)."""

    def test_sum_chunks(self, comm):
        x = stacked((3, 4))
        out = np.asarray(comm.reduce_scatter(x))
        flat = x.sum(0).reshape(-1)  # 12 elements over 8 ranks: chunk 2
        chunk = -(-12 // N)
        padded = np.zeros(chunk * N, np.float32)
        padded[:12] = flat
        assert out.shape == (N, chunk)
        for r in range(N):
            np.testing.assert_allclose(
                out[r], padded[r * chunk:(r + 1) * chunk], rtol=1e-5)

    def test_mean(self, comm):
        x = stacked((5,))
        out = np.asarray(comm.reduce_scatter(x, op="mean"))
        want = np.asarray(comm.reduce_scatter(x)) / N
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_gather_inverts(self, comm):
        x = stacked((5,))
        rs = comm.reduce_scatter(x)
        ag = np.asarray(comm.all_gather_shard(rs))
        chunk = -(-5 // N)
        padded = np.zeros(chunk * N, np.float32)
        padded[:5] = x.sum(0)
        assert ag.shape == (N, chunk * N)
        for r in range(N):
            np.testing.assert_allclose(ag[r], padded, rtol=1e-5)

    def test_bucketed_bitwise(self, comm):
        x = stacked((7,), seed=3)
        a = np.asarray(comm.reduce_scatter(x))
        b = np.asarray(comm.reduce_scatter(x, bucket_bytes=4))
        np.testing.assert_array_equal(a, b)

    def test_pytree(self, comm):
        x = {"a": stacked((4,)), "b": stacked((6,), seed=1)}
        out = comm.reduce_scatter(x)
        assert set(out) == {"a", "b"}
        np.testing.assert_allclose(
            np.asarray(out["a"]),
            np.asarray(comm.reduce_scatter(x["a"])), rtol=1e-6)

    def test_bad_op(self, comm):
        with pytest.raises(ValueError, match="sum/mean"):
            comm.reduce_scatter(stacked((4,)), op="max")

    def test_bad_leading_axis(self, comm):
        with pytest.raises(ValueError):
            comm.reduce_scatter(np.ones((N + 1, 4), np.float32))
