"""Tests for kungfu_tpu.plan — mirrors reference Go unit tests
(srcs/go/plan/*_test.go, plan/graph/graph_test.go)."""

import pytest

from kungfu_tpu.plan import (
    Cluster,
    Graph,
    HostList,
    HostSpec,
    PeerID,
    PeerList,
    Strategy,
    auto_select,
    gen_binary_tree,
    gen_binary_tree_star,
    gen_circular_graph_pair,
    gen_multi_binary_tree_star,
    gen_multi_star,
    gen_star,
    gen_tree,
    parse_host_list,
    parse_strategy,
)
from kungfu_tpu.plan.hostfile import parse_hostfile_text
from kungfu_tpu.plan.peer import parse_peer_id


class TestPeer:
    def test_parse(self):
        p = parse_peer_id("10.0.0.1:10000")
        assert p == PeerID("10.0.0.1", 10000)
        assert str(p) == "10.0.0.1:10000"

    def test_parse_bad(self):
        with pytest.raises(ValueError):
            parse_peer_id("nocolon")


class TestPeerList:
    def test_ranks(self):
        pl = PeerList.parse("a:10000,a:10001,b:10000,b:10001")
        assert len(pl) == 4
        assert pl.rank(PeerID("b", 10000)) == 2
        assert pl.local_rank(PeerID("b", 10001)) == 1
        assert pl.local_size(PeerID("a", 10000)) == 2
        assert pl.hosts() == ["a", "b"]
        assert pl.partition_by_host() == {"a": [0, 1], "b": [2, 3]}
        assert pl.local_masters() == [0, 2]

    def test_diff(self):
        a = PeerList.parse("h:10000,h:10001")
        b = PeerList.parse("h:10001,h:10002")
        added, removed = a.diff(b)
        assert added == [PeerID("h", 10002)]
        assert removed == [PeerID("h", 10000)]

    def test_roundtrip(self):
        s = "x:1,y:2"
        assert str(PeerList.parse(s)) == s


class TestHostSpec:
    def test_parse_forms(self):
        assert HostSpec.parse("1.2.3.4") == HostSpec("1.2.3.4", 1, "1.2.3.4")
        assert HostSpec.parse("1.2.3.4:8").slots == 8
        assert HostSpec.parse("1.2.3.4:8:pub").public_addr == "pub"

    def test_host_list(self):
        hl = parse_host_list("a:2,b:2")
        assert hl.cap() == 4
        peers = hl.gen_peer_list(3)
        assert [str(p) for p in peers] == ["a:10000", "a:10001", "b:10000"]
        runners = hl.gen_runner_list()
        assert [p.port for p in runners] == [38080, 38080]

    def test_np_exceeds_cap(self):
        with pytest.raises(ValueError):
            parse_host_list("a:1").gen_peer_list(2)

    def test_duplicate_host(self):
        with pytest.raises(ValueError):
            parse_host_list("a:1,a:2")

    def test_hostfile(self):
        hl = parse_hostfile_text("10.0.0.1 slots=4\n# cmt\n10.0.0.2\n")
        assert hl.cap() == 5


class TestGraph:
    def test_forest_roundtrip(self):
        f = [0, 0, 0, 1, 1, 2]
        g = Graph.from_forest_array(f)
        assert g.to_forest_array() == f
        assert g.is_self_loop(0)
        assert set(g.nexts(0)) == {1, 2}

    def test_cycle_detected(self):
        with pytest.raises(ValueError):
            Graph.from_forest_array([1, 0])

    def test_reverse(self):
        g = Graph(3)
        g.add_edge(0, 1)
        g.add_edge(0, 2)
        r = g.reverse()
        assert set(r.nexts(1)) == {0}
        assert set(r.prevs(0)) == {1, 2}

    def test_digest_equality(self):
        a = Graph.from_forest_array([0, 0, 1])
        b = Graph.from_forest_array([0, 0, 1])
        c = Graph.from_forest_array([0, 0, 0])
        assert a == b
        assert a != c


def _check_broadcast_tree(b, n, expect_root=None):
    """Every node reachable exactly once from the root."""
    roots = [i for i in range(n) if b.is_self_loop(i)]
    assert len(roots) == 1
    if expect_root is not None:
        assert roots[0] == expect_root
    seen = set()
    stack = [roots[0]]
    while stack:
        i = stack.pop()
        assert i not in seen
        seen.add(i)
        stack.extend(b.nexts(i))
    assert seen == set(range(n))


def _check_reduce_graph(r, n):
    # every node contributes itself
    for i in range(n):
        assert r.is_self_loop(i)


class TestTopology:
    @pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 8, 16])
    def test_star(self, n):
        red, bc = gen_star(n)
        _check_broadcast_tree(bc, n, expect_root=0)
        _check_reduce_graph(red, n)

    @pytest.mark.parametrize("n", [1, 2, 5, 8])
    def test_tree_families(self, n):
        for gen in (gen_tree, gen_binary_tree):
            red, bc = gen(n)
            _check_broadcast_tree(bc, n)
            _check_reduce_graph(red, n)

    @pytest.mark.parametrize("hosts,n", [([[0, 1], [2, 3]], 4), ([[0, 1, 2, 3], [4, 5, 6, 7]], 8), ([[0]], 1)])
    def test_binary_tree_star(self, hosts, n):
        red, bc = gen_binary_tree_star(n, hosts)
        _check_broadcast_tree(bc, n)
        _check_reduce_graph(red, n)

    def test_multi_binary_tree_star(self):
        pairs = gen_multi_binary_tree_star(4, [[0, 1], [2, 3]])
        assert len(pairs) == 2
        for red, bc in pairs:
            _check_broadcast_tree(bc, 4)

    def test_multi_star_single_host(self):
        # one host -> one pure local star
        pairs = gen_multi_star(3)
        assert len(pairs) == 1
        _check_broadcast_tree(pairs[0][1], 3, expect_root=0)

    def test_multi_star_host_aware(self):
        # reference GenMultiStar (topology.go:117-125): per-host local
        # stars + a rotated star over the masters, one pair per master
        hosts = [[0, 1], [2, 3], [4, 5]]
        pairs = gen_multi_star(6, hosts)
        assert len(pairs) == 3
        masters = [0, 2, 4]
        for i, (red, bc) in enumerate(pairs):
            _check_broadcast_tree(bc, 6, expect_root=masters[i])
            # local edges identical in every rotation
            for ranks in hosts:
                assert ranks[1] in bc.nexts(ranks[0])
            # cross edges: center -> other masters
            for m in masters:
                if m != masters[i]:
                    assert m in bc.nexts(masters[i])

    def test_tree_host_aware(self):
        # reference GenTree (topology.go:17-31): local stars + star of
        # masters centered at the first
        red, bc = gen_tree(4, [[0, 1], [2, 3]])
        assert bc.is_self_loop(0)
        assert set(bc.nexts(0)) == {1, 2}
        assert set(bc.nexts(2)) == {3}
        _check_broadcast_tree(bc, 4, expect_root=0)
        _check_reduce_graph(red, 4)

    def test_cross_ring_pairs(self):
        from kungfu_tpu.plan.topology import gen_cross_ring_pairs

        masters = [0, 2, 4]
        pairs = gen_cross_ring_pairs(6, masters)
        assert len(pairs) == 3
        for red, bc in pairs:
            # only masters participate: non-masters have no edges/loops
            for r in (1, 3, 5):
                assert not red.prevs(r) and not red.nexts(r)
                assert not red.is_self_loop(r)
            # reduce chain covers all masters, ends where bcast starts
            ends = [m for m in masters if not red.nexts(m)]
            assert len(ends) == 1 and bc.is_self_loop(ends[0])

    def test_cross_binary_tree(self):
        from kungfu_tpu.plan.topology import gen_cross_binary_tree

        ((red, bc),) = gen_cross_binary_tree(7, [0, 2, 4, 6])
        assert set(bc.nexts(0)) == {2, 4}
        assert set(bc.nexts(2)) == {6}
        for r in (1, 3, 5):
            assert not red.is_self_loop(r) and not bc.nexts(r)
        for m in (0, 2, 4, 6):
            assert red.is_self_loop(m)

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_ring(self, n):
        red, bc = gen_circular_graph_pair(n)
        # reduce chain ends where broadcast starts
        _check_reduce_graph(red, n)
        ends = [i for i in range(n) if not red.nexts(i)]
        assert len(ends) == 1
        assert bc.is_self_loop(ends[0])


class TestStrategy:
    def test_parse(self):
        assert parse_strategy("ring") == Strategy.RING
        assert parse_strategy("binary-tree-star") == Strategy.BINARY_TREE_STAR
        with pytest.raises(ValueError):
            parse_strategy("nope")

    def test_auto(self):
        # single host diverges from the reference's STAR: colocated RING
        # measured ~20% faster over unix sockets (strategy.py:auto_select)
        assert auto_select(1) == Strategy.RING
        assert auto_select(3) == Strategy.BINARY_TREE_STAR


class TestCluster:
    def _cluster(self, spec="a:4,b:4", np=4):
        hl = HostList.parse(spec)
        return Cluster(hl.gen_runner_list(), hl.gen_peer_list(np))

    def test_json_roundtrip(self):
        c = self._cluster()
        c2 = Cluster.from_json(c.to_json())
        assert c2 == c
        assert c.digest() == c2.digest()

    def test_validate_orphan_worker(self):
        with pytest.raises(ValueError):
            Cluster(
                PeerList.parse("a:38080"),
                PeerList.parse("b:10000"),
            ).validate()

    def test_shrink(self):
        c = self._cluster(np=4).resize(2)
        assert c.size() == 2
        assert [str(p) for p in c.workers] == ["a:10000", "a:10001"]

    def test_grow(self):
        c = self._cluster(np=2)  # both on host a
        g = c.resize(4)
        assert g.size() == 4
        hosts = [p.host for p in g.workers]
        assert hosts.count("b") >= 1  # grew onto the empty host first

    def test_grow_beyond_capacity(self):
        hl = HostList.parse("a:1")
        c = Cluster(hl.gen_runner_list(), hl.gen_peer_list(1))
        # port-range capacity is large; grow within range works
        assert c.resize(3).size() == 3

    def test_digest_changes(self):
        c = self._cluster()
        assert c.digest() != c.resize(2).digest()
