"""Policy subsystem tests — parity with reference
tests/python/unit/test_tensorflow_policy.py (policy scheduling) plus the
GNS-driven resize heuristic."""

from kungfu_tpu.policy import (
    BasePolicy,
    GNSResizePolicy,
    PolicyContext,
    PolicyRunner,
    ScheduledSizePolicy,
)


class Recorder(BasePolicy):
    def __init__(self):
        self.calls = []

    def before_train(self, ctx):
        self.calls.append("before_train")

    def after_train(self, ctx):
        self.calls.append("after_train")

    def before_epoch(self, ctx):
        self.calls.append("before_epoch")

    def after_epoch(self, ctx):
        self.calls.append("after_epoch")

    def before_step(self, ctx):
        self.calls.append("before_step")

    def after_step(self, ctx):
        self.calls.append("after_step")


class TestLifecycle:
    def test_callback_order_and_globals(self):
        rec = Recorder()
        r = PolicyRunner([rec], batch_size=32)
        r.before_train()
        r.before_epoch()
        for _ in range(3):
            r.before_step()
            params, stop = r.after_step(params={"w": 1})
            assert not stop
        r.after_epoch()
        r.after_train()
        assert rec.calls == (
            ["before_train", "before_epoch"]
            + ["before_step", "after_step"] * 3
            + ["after_epoch", "after_train"]
        )
        assert r.ctx.step == 3
        assert r.ctx.trained_samples == 3 * 32  # cluster_size 1
        assert r.ctx.epoch == 1

    def test_stop_request(self):
        class Stopper(BasePolicy):
            def after_step(self, ctx):
                if ctx.step >= 2:
                    ctx.request_stop()

        r = PolicyRunner([Stopper()])
        assert r.after_step()[1] is False
        assert r.after_step()[1] is True

    def test_resize_intent_without_peer_is_noop(self):
        r = PolicyRunner([ScheduledSizePolicy("1:1,4:100")])
        params, stop = r.after_step(params=None)
        assert not stop
        assert r.ctx.requested_size is None  # consumed


class TestScheduledSizePolicy:
    def test_requests_schedule_size(self):
        p = ScheduledSizePolicy("1:2,2:2,4:10")
        ctx = PolicyContext(cluster_size=1)
        ctx.step = 1
        p.after_step(ctx)
        assert ctx.requested_size is None  # still in 1-phase
        ctx.step = 3
        p.after_step(ctx)
        assert ctx.requested_size == 2


class TestGNSResizePolicy:
    def test_grows_when_gns_large(self):
        p = GNSResizePolicy(max_size=16)
        ctx = PolicyContext(batch_size=64, cluster_size=2)
        ctx.step = 100
        ctx.gradient_noise_scale = 512.0  # → want 8 workers
        p.after_step(ctx)
        assert ctx.requested_size == 8

    def test_hysteresis_band_holds(self):
        p = GNSResizePolicy()
        ctx = PolicyContext(batch_size=64, cluster_size=8)
        ctx.gradient_noise_scale = 64.0 * 9  # want 9, within 50% of 8
        p.after_step(ctx)
        assert ctx.requested_size is None

    def test_no_signal_no_action(self):
        p = GNSResizePolicy()
        ctx = PolicyContext(batch_size=64, cluster_size=4)
        p.after_step(ctx)
        assert ctx.requested_size is None

    def test_cooldown(self):
        p = GNSResizePolicy(cooldown_steps=10, max_size=64)
        ctx = PolicyContext(batch_size=32, cluster_size=2)
        ctx.step = 1
        ctx.gradient_noise_scale = 32.0 * 16
        p.after_step(ctx)
        assert ctx.requested_size == 16
        ctx.requested_size = None
        ctx.cluster_size = 2  # resize did not happen (e.g. no server)
        ctx.step = 5  # within cooldown
        p.after_step(ctx)
        assert ctx.requested_size is None
        ctx.step = 12
        p.after_step(ctx)
        assert ctx.requested_size == 16
