"""kf-xray: causal tracing, critical-path attribution, MFU (tier-1).

Covers the cost model (analytic params/FLOPs pinned against a real
``init()`` tree), the timeline causal triple (derived collective trace
ids, ambient ``trace_ctx``, wire-format round-trip), the pure
attribution math (interval union, phase split, critical path, verdict
determinism), the REPORT_KINDS⊇XRAY_KINDS contract the offline==online
guarantee rests on, the chaos-run satellite (a planted 30 ms link delay
must be attributed identically by ``kftrace --critical-path`` and the
live aggregator, naming the planted edge), and the serve-plane
distributed trace (router → worker → engine as ONE trace id).
See docs/xray.md.
"""

import json
import threading
import time

import numpy as np
import pytest

from kungfu_tpu.monitor import skew as skewlib
from kungfu_tpu.monitor import timeline, traceview
from kungfu_tpu.monitor import xray as xraylib
from kungfu_tpu.monitor.aggregator import (REPORT_KINDS, ClusterAggregator,
                                           make_snapshot)
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.ops import costmodel


@pytest.fixture(autouse=True)
def _fresh_timeline():
    timeline.reset()
    yield
    timeline.reset()


# -- cost model -------------------------------------------------------------
class TestCostModel:
    def _count_leaves(self, tree):
        import jax

        return sum(int(np.prod(leaf.shape))
                   for leaf in jax.tree_util.tree_leaves(tree))

    @pytest.mark.parametrize("pos", ["rope", "learned"])
    def test_param_count_matches_real_init(self, pos):
        import jax

        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)

        cfg = TransformerConfig(vocab_size=96, d_model=32, n_layers=2,
                                n_heads=2, d_ff=64, max_seq=16, pos=pos)
        params = Transformer(cfg).init(jax.random.PRNGKey(0))
        assert (costmodel.transformer_param_count(cfg)
                == self._count_leaves(params))

    def test_train_is_three_forwards_and_layers_scale(self):
        from kungfu_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                                n_heads=4, d_ff=256, max_seq=64)
        fwd = costmodel.forward_flops(cfg, 4, 32)
        assert costmodel.train_step_flops(cfg, 4, 32) == 3 * fwd
        cfg4 = TransformerConfig(vocab_size=128, d_model=64, n_layers=4,
                                 n_heads=4, d_ff=256, max_seq=64)
        # doubling depth doubles everything except the (depth-free) head
        head = 2 * 4 * 32 * cfg.d_model * cfg.vocab_size
        assert (costmodel.forward_flops(cfg4, 4, 32) - head
                == 2 * (fwd - head))

    def test_prefill_equals_decode_sum_modulo_heads(self):
        """Prefilling t tokens does the same matmul+attention work as t
        decode steps over the growing context; only the LM head differs
        (prefill computes ONE logits row, decode computes one per
        token)."""
        from kungfu_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                                n_heads=4, d_ff=256, max_seq=64)
        t = 7
        head = 2 * cfg.d_model * cfg.vocab_size
        decode_sum = sum(costmodel.serve_decode_flops(cfg, i)
                         for i in range(1, t + 1))
        assert costmodel.serve_prefill_flops(cfg, t) == (
            decode_sum - (t - 1) * head)

    def test_prefill_with_cached_prefix_costs_less(self):
        from kungfu_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                                n_heads=4, d_ff=256, max_seq=64)
        full = costmodel.serve_prefill_flops(cfg, 16, start=0)
        suffix = costmodel.serve_prefill_flops(cfg, 8, start=8)
        assert 0 < suffix < full
        assert costmodel.serve_prefill_flops(cfg, 0, start=16) == 0

    def test_peak_env_override_wins(self, monkeypatch):
        monkeypatch.setenv(costmodel.PEAK_ENV, "1e15")
        assert costmodel.chip_peak_flops() == 1e15
        monkeypatch.setenv(costmodel.PEAK_ENV, "not-a-number")
        # malformed override falls through to detection (CPU -> None)
        assert costmodel.chip_peak_flops() is None

    def test_kv_bytes_per_token(self):
        from kungfu_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(vocab_size=128, d_model=64, n_layers=3,
                                n_heads=4, d_ff=256, max_seq=64)
        # K+V, per layer, head_dim x heads, bf16
        assert costmodel.kv_bytes_per_token(cfg) == 2 * 3 * 64 * 2

    def test_mfu_meter_gauges_and_xray_mark(self, monkeypatch):
        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        meter = costmodel.MFUMeter(step_flops=1_000_000, peak_flops=1e8)
        rate = meter.step(wall_s=0.1,
                          phases={"compute": 0.08, "comm_exposed": 0.02})
        assert rate == pytest.approx(1e7)
        assert meter.mfu == pytest.approx(0.1)
        snap = REGISTRY.snapshot()
        assert snap["kf_mfu"] == pytest.approx(0.1)
        assert snap["kf_model_flops_s"] == pytest.approx(1e7)
        assert snap['kf_step_phase_seconds{phase="compute"}'] == (
            pytest.approx(0.08))
        marks = [e for e in timeline.snapshot() if e["kind"] == "xray"]
        assert marks and marks[-1]["attrs"]["mfu"] == pytest.approx(0.1)

    def test_mfu_meter_accumulates_serving_flops(self):
        meter = costmodel.MFUMeter(peak_flops=None, detect_peak=False)
        meter.add_flops(500)
        meter.add_flops(500)
        assert meter.step(wall_s=0.001) == pytest.approx(1e6)
        assert meter.mfu is None  # no peak -> model-FLOPs rate only


# -- causal triple (timeline) ----------------------------------------------
class TestTraceContext:
    def test_collective_trace_id_is_pure(self):
        a = timeline.collective_trace_id(3, 17, "all_reduce", "ar5")
        assert a == timeline.collective_trace_id(3, 17, "all_reduce", "ar5")
        assert a != timeline.collective_trace_id(4, 17, "all_reduce", "ar5")

    def test_wire_form_round_trip(self):
        tc = timeline.format_trace_context("srv.r1", "s0.7")
        assert timeline.parse_trace_context(tc) == ("srv.r1", "s0.7")
        assert timeline.format_trace_context("t") == "t"
        assert timeline.parse_trace_context("t") == ("t", None)
        assert timeline.parse_trace_context(None) == (None, None)
        assert timeline.parse_trace_context(7) == (None, None)
        # an empty trace id must stay unlinked, never group as ""
        assert timeline.parse_trace_context("@x") == (None, None)
        assert timeline.context_attrs("", "x") == {}
        assert timeline.context_attrs("t") == {"trace": "t"}
        assert timeline.context_attrs("t", "p") == {"trace": "t",
                                                    "parent": "p"}
        assert timeline.format_trace_context(None) is None

    def test_span_triple_nests(self):
        with timeline.span("collective", "outer", force=True,
                           trace="T1") as outer:
            with timeline.span("device", "inner", force=True) as inner:
                timeline.event("mark", "leaf", force=True)
        evs = {e["name"]: e for e in timeline.snapshot()}
        assert evs["outer"]["attrs"]["trace"] == "T1"
        assert evs["outer"]["attrs"]["span"] == outer.span_id
        assert "parent" not in evs["outer"]["attrs"]
        # the inner span inherits the trace and hangs off the outer span
        assert evs["inner"]["attrs"]["trace"] == "T1"
        assert evs["inner"]["attrs"]["parent"] == outer.span_id
        # the mark inherits from the innermost enclosing span
        assert evs["leaf"]["attrs"]["trace"] == "T1"
        assert evs["leaf"]["attrs"]["parent"] == inner.span_id

    def test_trace_ctx_reenters_received_context(self):
        with timeline.trace_ctx("srv.9", "s0.router"):
            timeline.event("serve", "request-recv", force=True)
        ev = timeline.snapshot()[-1]
        assert ev["attrs"]["trace"] == "srv.9"
        assert ev["attrs"]["parent"] == "s0.router"

    def test_explicit_trace_wins_over_ambient(self):
        with timeline.trace_ctx("ambient"):
            timeline.event("mark", "m", force=True, trace="explicit")
        assert timeline.snapshot()[-1]["attrs"]["trace"] == "explicit"

    def test_span_ids_unique_and_reset(self):
        with timeline.span("mark", "a", force=True) as a:
            pass
        with timeline.span("mark", "b", force=True) as b:
            pass
        assert a.span_id != b.span_id
        timeline.reset()
        with timeline.span("mark", "c", force=True) as c:
            pass
        assert c.span_id == a.span_id  # counter re-anchored per capture

    def test_threads_have_independent_ambient_context(self):
        seen = {}

        def other():
            seen["ctx"] = timeline.current_trace()

        with timeline.trace_ctx("T", "p"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["ctx"] == (None, None)


# -- pure attribution math --------------------------------------------------
def _span_ev(rank, step, ts, dur, op="all_reduce", tag="ar0",
             kind="collective", **attrs):
    return {"ts": ts, "rank": rank, "step": step, "kind": kind,
            "name": f"engine.{op}", "dur": dur,
            "attrs": {"op": op, "tag": tag, **attrs}}


def _mark_ev(rank, step, ts, kind, name, **attrs):
    return {"ts": ts, "rank": rank, "step": step, "kind": kind,
            "name": name, "dur": 0.0, "attrs": attrs}


class TestXrayMath:
    def test_union_len_merges_overlaps(self):
        assert xraylib._union_len([]) == 0.0
        assert xraylib._union_len([(0, 1), (0.5, 2), (3, 4)]) == (
            pytest.approx(3.0))
        assert xraylib._union_len([(1, 1), (2, 1)]) == 0.0  # degenerate

    def test_rank_phase_split(self):
        evs = [
            _span_ev(0, 1, 10.0, 0.3, tag="sync"),          # exposed
            _span_ev(0, 1, 10.4, 0.2, tag="async"),         # hidden
            _mark_ev(0, 1, 10.35, "overlap", "issue", tag="async"),
            {"ts": 10.7, "rank": 0, "step": 1, "kind": "input",
             "name": "prefetch.next", "dur": 0.1, "attrs": {}},
            _mark_ev(0, 1, 11.0, "overlap", "complete", tag="async"),
        ]
        split = xraylib.rank_phase_split(evs)
        assert split["wall_s"] == pytest.approx(1.0)
        assert split["comm_exposed"] == pytest.approx(0.3)
        assert split["comm_hidden"] == pytest.approx(0.2)
        assert split["input_stall"] == pytest.approx(0.1)
        assert split["compute"] == pytest.approx(0.4)

    def test_step_attribution_names_culprit_edge(self):
        evs = [
            _span_ev(0, 2, 100.0, 0.01, tag="g"),
            _span_ev(1, 2, 100.0, 0.06, tag="g"),   # the straggler
            _span_ev(2, 2, 100.0, 0.02, tag="g"),
        ]
        rows = xraylib.step_attribution(evs)
        assert len(rows) == 1
        r = rows[0]
        assert r["step"] == 2 and r["critical_rank"] == 1
        assert r["culprit"]["slowest_rank"] == 1
        assert r["culprit"]["fastest_rank"] == 0
        assert r["phases"]["straggler_wait"] == pytest.approx(0.05)
        # critical rank's comm minus the skew excess
        assert r["phases"]["comm_exposed"] == pytest.approx(0.01)

    def test_critical_path_orders_barriers_and_gaps(self):
        evs = [
            _span_ev(0, 1, 10.0, 0.02, tag="a"),
            _span_ev(1, 1, 10.0, 0.05, tag="a"),
            _span_ev(0, 1, 10.2, 0.04, tag="b"),
            _span_ev(1, 1, 10.2, 0.01, tag="b"),
        ]
        hops = xraylib.critical_path(evs, step=1)
        kinds = [(h["kind"], h.get("tag"), h["rank"]) for h in hops]
        assert kinds == [("collective", "a", 1), ("gap", None, 0),
                         ("collective", "b", 0)]
        assert hops[1]["dur_s"] == pytest.approx(0.15)
        assert hops[0]["skew_s"] == pytest.approx(0.03)

    def test_verdict_matches_skew_and_is_deterministic(self):
        evs = [_span_ev(r, s, 100.0 + s, 0.01 * (r + 1) + 0.05 * (r == 2),
                        tag=f"t{s}")
               for r in range(3) for s in range(4)]
        v1 = xraylib.verdict(evs)
        v2 = xraylib.verdict(list(reversed(evs)))  # arrival order moot
        assert v1 == v2
        assert v1["straggler"] == skewlib.straggler_verdict(evs)
        assert v1["steps_seen"] == 4

    def test_report_kinds_superset_contract(self):
        """The offline==online guarantee: every kind the attribution
        consumes must be forwarded by the live reporter."""
        assert xraylib.XRAY_KINDS <= REPORT_KINDS
        assert xraylib.XRAY_KINDS <= timeline.EVENT_KINDS

    def test_online_view_none_when_nothing_attributable(self):
        assert xraylib.online_view([]) is None
        assert xraylib.render_report([]).startswith("kf-xray: 0")

    def test_window_env(self, monkeypatch):
        monkeypatch.setenv(xraylib.WINDOW_ENV, "3")
        evs = [_span_ev(r, s, 100.0 + s, 0.01 + 0.01 * r, tag=f"t{s}")
               for r in range(2) for s in range(9)]
        view = xraylib.online_view(evs)
        assert len(view["steps"]) == 3
        assert view["verdict"]["steps_seen"] == 3


# -- the chaos satellite: offline == online, planted edge named -------------
def _make_peers(base_port, n=3):
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.plan import Cluster, PeerList, parse_strategy
    from kungfu_tpu.utils.envs import Config

    workers = PeerList.parse(
        ",".join(f"127.0.0.1:{base_port + i}" for i in range(n)))
    runners = PeerList.parse(f"127.0.0.1:{base_port + 99}")
    cluster = Cluster(runners, workers)
    peers = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
    for p in peers:
        p.config.strategy = parse_strategy("STAR")
        p.start()
    return peers


def _run_world(fns, timeout=60.0):
    outs, errs = [None] * len(fns), []

    def wrap(i, f):
        try:
            outs[i] = f()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i, f), daemon=True)
          for i, f in enumerate(fns)]
    for t in ts:
        t.start()
    deadline = time.monotonic() + timeout
    for t in ts:
        t.join(max(0.0, deadline - time.monotonic()))
    if errs:
        raise errs[0]
    assert not any(t.is_alive() for t in ts), "xray world hung"
    return outs


class TestChaosAttribution:
    def test_planted_link_delay_attributed_identically(self, monkeypatch,
                                                       tmp_path):
        """ISSUE 14 satellite: 3-rank chaos run with 30 ms planted on
        the 0<->1 link — the offline critical path (through the REAL
        kftrace dump+load path) and the online aggregator verdict name
        the planted slow edge, asserted identical."""
        from kungfu_tpu import chaos

        wire_ms = 30
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        monkeypatch.setenv(
            "KF_CHAOS_SPEC",
            f"delay:ms={wire_ms},rank=0,peer=1,on=send;"
            f"delay:ms={wire_ms},rank=1,peer=0,on=send;"
            f"delay:ms={2 * wire_ms},rank=1,peer=0,on=recv")
        chaos.reset()
        peers = _make_peers(27310)
        buf = np.ones(20_000, np.float32)
        timeline.reset()
        try:
            for step in range(6):
                timeline.set_step(step)
                _run_world([
                    lambda p=p: p.engine().all_reduce(buf, op="sum")
                    for p in peers])
        finally:
            for p in peers:
                p.close()
            chaos.reset()
        events = timeline.snapshot()
        # offline: dump -> kftrace load path -> verdict
        dump = tmp_path / "xray.jsonl"
        timeline.dump(str(dump))
        loaded = traceview.load_all([str(dump)])
        offline = xraylib.verdict(loaded)
        # online: live aggregator fed REPORT_KINDS-filtered snapshots
        agg = ClusterAggregator(stale_after=3600.0)
        for r in range(3):
            agg.ingest(make_snapshot(
                rank=r, pid=0, wall=time.time(), step=5, step_time_s=0.1,
                counters={}, gauges={}, latency={},
                events=[e for e in events
                        if e["rank"] == r and e["kind"] in REPORT_KINDS],
                net={}, strategy="STAR"))
        online = (agg.cluster_view()["xray"] or {})["verdict"]
        # ONE implementation: the verdicts are identical, not just alike
        assert json.loads(json.dumps(offline)) == json.loads(
            json.dumps(online))
        # ...and they name the planted edge: rank 1 (the delayed legs)
        assert offline["straggler"] == 1
        assert offline["culprit"]["slowest_rank"] == 1
        assert offline["culprit"]["skew_s"] >= 0.5 * wire_ms / 1e3
        assert offline["dominant"] == "comm_exposed"
        # the spans carry the derived cross-rank trace id: same step +
        # tag -> same trace on every rank, no wire bytes spent
        colls = [e for e in loaded if e["kind"] == "collective"
                 and e["step"] == 3]
        by_trace = {}
        for e in colls:
            by_trace.setdefault(e["attrs"]["trace"], set()).add(e["rank"])
        assert any(ranks == {0, 1, 2} for ranks in by_trace.values())
        # the offline CLI renders the same culprit
        report = xraylib.render_report(loaded)
        assert "culprit edge" in report and "rank 1" in report

    def test_kftrace_critical_path_cli(self, monkeypatch, tmp_path,
                                       capsys):
        timeline.reset()
        with timeline.span("collective", "engine.all_reduce", rank=0,
                           force=True, op="all_reduce", tag="t0"):
            time.sleep(0.002)
        dump = tmp_path / "d.jsonl"
        timeline.dump(str(dump))
        assert traceview.main(["--critical-path", str(dump)]) == 0
        out = capsys.readouterr().out
        assert "kf-xray:" in out and "per-step attribution" in out
        # no dumps -> usage error, not a crash
        assert traceview.main(["--critical-path"]) == 2


# -- serve plane: one trace router -> worker -> engine ----------------------
class TestServeDistributedTrace:
    def test_one_request_is_one_trace(self, monkeypatch):
        import jax

        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        from kungfu_tpu.serve.engine import InferenceEngine
        from kungfu_tpu.serve.kvcache import KVCachePool, PageSpec
        from kungfu_tpu.serve.router import ServeRouter, ServeWorker

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                                n_heads=2, d_ff=64, max_seq=128,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        peers = _make_peers(27350, n=2)
        timeline.reset()
        eng = InferenceEngine(
            model, params,
            pool=KVCachePool(PageSpec.for_model(cfg, page_tokens=8),
                             capacity_pages=64),
            max_batch=2, max_seq=cfg.max_seq, rank=0)
        eng.warmup(prompt_lens=(4,))
        worker = ServeWorker(peers[0], eng, commit_every=2).start()
        router = ServeRouter(peers[1], worker_ranks=[0])
        try:
            h = router.submit([1, 2, 3], 6)
            toks = h.wait(60)
            assert len(toks) == 6
            trace = h.trace
            evs = [e for e in timeline.snapshot()
                   if (e["attrs"] or {}).get("trace") == trace]
            kinds = {(e["kind"], e["name"]) for e in evs}
            # router admission + completion, the worker's frame receipt,
            # and the engine's prefill span: ONE distributed trace
            assert ("request", "accept") in kinds
            assert ("request", "complete") in kinds
            assert ("serve", "request-recv") in kinds
            assert ("serve", "prefill") in kinds
            prefill = next(e for e in evs if e["name"] == "prefill")
            assert prefill["attrs"]["parent"] == h.router_span
            recv = next(e for e in evs if e["name"] == "request-recv")
            assert recv["attrs"]["parent"] == h.router_span
        finally:
            router.close()
            worker.stop()
            for p in peers:
                p.close()

    def test_serving_engine_exports_model_flops_rate(self, monkeypatch):
        import jax

        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        from kungfu_tpu.serve.engine import InferenceEngine
        from kungfu_tpu.serve.kvcache import KVCachePool, PageSpec

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                                n_heads=2, d_ff=64, max_seq=128,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        REGISTRY.gauge("kf_model_flops_s").set(0.0)
        eng = InferenceEngine(
            model, params,
            pool=KVCachePool(PageSpec.for_model(cfg, page_tokens=8),
                             capacity_pages=64),
            max_batch=2, max_seq=cfg.max_seq, rank=0)
        eng.submit("r1", [1, 2, 3, 4], 8)
        eng.drain()
        assert REGISTRY.snapshot()["kf_model_flops_s"] > 0
        assert eng._mfu.mfu is None  # CPU: rate only, no fake MFU


# -- aggregator / kftop flow ------------------------------------------------
class TestXrayLivePlane:
    def _snap(self, rank, events, gauges=None, counters=None):
        return make_snapshot(
            rank=rank, pid=0, wall=time.time(), step=1, step_time_s=0.1,
            counters=counters or {}, gauges=gauges or {}, latency={},
            events=events, net={}, strategy="")

    def test_cluster_view_xray_section_and_prometheus(self):
        agg = ClusterAggregator(stale_after=3600.0)
        for r in range(2):
            agg.ingest(self._snap(
                r, [_span_ev(r, 1, 50.0, 0.01 + 0.04 * r, tag="g")],
                gauges=({"kf_mfu": 0.37, "kf_model_flops_s": 2e12,
                         'kf_step_phase_seconds{phase="compute"}': 0.2}
                        if r == 0 else None),
                counters={"kf_timeline_dropped_total": 9} if r else None))
        view = agg.cluster_view()
        xr = view["xray"]
        assert xr["verdict"]["culprit"]["slowest_rank"] == 1
        assert xr["mfu"] == {0: 0.37}
        assert xr["model_flops_s"] == pytest.approx(2e12)
        assert xr["phase_seconds"] == {"compute": pytest.approx(0.2)}
        assert xr["dropped_events"] == {1: 9}
        prom = agg.render_prometheus()
        assert 'kf_cluster_mfu{rank="0"} 0.37' in prom
        assert "kf_cluster_model_flops_s 2e+12" in prom
        assert 'kf_cluster_step_phase_seconds{phase="compute"}' in prom

    def test_kftop_renders_xray_and_trace_loss(self):
        from kungfu_tpu.monitor import kftop

        agg = ClusterAggregator(stale_after=3600.0)
        agg.ingest(self._snap(
            0, [_span_ev(0, 1, 50.0, 0.01, tag="g"),
                _span_ev(1, 1, 50.0, 0.05, tag="g")],
            gauges={"kf_mfu": 0.37},
            counters={"kf_timeline_dropped_total": 4}))
        text = kftop.render_view(json.loads(json.dumps(agg.cluster_view())))
        assert "== XRAY" in text
        assert "culprit" in text and "rank 1" in text
        assert "TRACE LOSS" in text and "rank 0: 4" in text

    def test_phase_gauges_average_across_ranks(self):
        """The cluster phase rollup is the MEAN over exporting ranks —
        kftop renders it under a per-step label, and a 4-rank sum would
        read as a 4x-inflated step."""
        agg = ClusterAggregator(stale_after=3600.0)
        for r in range(4):
            agg.ingest(self._snap(
                r, [_span_ev(r, 1, 50.0, 0.01, tag="g")],
                gauges={'kf_step_phase_seconds{phase="compute"}': 0.1,
                        "kf_model_flops_s": 1e9}))
        xr = agg.cluster_view()["xray"]
        assert xr["phase_seconds"] == {"compute": pytest.approx(0.1)}
        # rates DO sum across ranks
        assert xr["model_flops_s"] == pytest.approx(4e9)

    def test_trace_loss_survives_unattributable_window(self):
        """A lossy ring alone must keep the xray section (and the kftop
        TRACE LOSS alarm) alive even when the surviving window holds
        nothing attributable — that is exactly when drops matter."""
        from kungfu_tpu.monitor import kftop

        agg = ClusterAggregator(stale_after=3600.0)
        agg.ingest(self._snap(0, [],
                              counters={"kf_timeline_dropped_total": 12}))
        view = agg.cluster_view()
        assert view["xray"]["dropped_events"] == {0: 12}
        assert view["xray"]["verdict"] is None
        text = kftop.render_view(json.loads(json.dumps(view)))
        assert "TRACE LOSS" in text and "rank 0: 12" in text

    def test_kftop_window_mean_fallback_divides_totals(self):
        """Without per-step gauges the XRAY phases render as the window
        MEAN per step, never the raw multi-step totals."""
        from kungfu_tpu.monitor import kftop

        agg = ClusterAggregator(stale_after=3600.0)
        evs = [_span_ev(r, s, 50.0 + s, 0.1, tag=f"g{s}")
               for r in range(2) for s in range(4)]
        agg.ingest(self._snap(0, evs))
        text = kftop.render_view(json.loads(json.dumps(agg.cluster_view())))
        assert "window mean" in text
        # 4 steps x 100 ms comm must render ~100 ms/step, not ~400 ms
        assert "comm_exposed 400.0ms" not in text

    def test_kftop_self_check_still_green(self):
        from kungfu_tpu.monitor import kftop

        assert kftop.self_check() == 0

    def test_kftrace_self_check_covers_serve_kinds(self, capsys):
        assert traceview.self_check([]) == 0
        assert "serve/request" in capsys.readouterr().out
