"""Tests for the 4-D parallelism subsystem on the 8-device CPU mesh.

Methodology mirrors the reference's cross-checking strategy (SURVEY §4:
"cross-checking its collectives against jax.lax references"): every sharded
path is compared numerically against the unsharded single-device model.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from kungfu_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kungfu_tpu.models.transformer import Transformer, TransformerConfig, default_attention
from kungfu_tpu.parallel import (
    MeshPlan,
    ShardedTrainer,
    moe_apply,
    moe_init,
    ring_attention,
)

CFG = dict(
    vocab_size=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
    max_seq=32, causal=True, pos="rope", dtype="float32",
)


def _batch(B=8, S=32, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, vocab, size=(B, S)), dtype=jnp.int32)
    tgt = jnp.asarray(rng.integers(0, vocab, size=(B, S)), dtype=jnp.int32)
    return ids, tgt


# -- mesh plan ------------------------------------------------------------
def test_mesh_plan_auto():
    p = MeshPlan.auto(8)
    assert p.size == 8
    assert p.dp == 2 and p.tp == 2 and p.sp == 2 and p.pp == 1
    p16 = MeshPlan.auto(16)
    assert p16.size == 16 and p16.pp == 2
    assert MeshPlan.auto(1).size == 1
    assert MeshPlan.auto(6).size == 6


# -- ring attention -------------------------------------------------------
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    n_sp = 4
    B, H, S, D = 2, 2, 32, 16
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:n_sp]), ("sp",))
    f = shard_map(
        functools.partial(ring_attention, causal=causal, axis="sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = jax.jit(f)(q, k, v)
    ref = default_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_blocks_match_dense(causal):
    """block_impl='flash': per-round Pallas blocks (interpret mode here)
    merged by lse must equal dense attention — including the skipped
    fully-masked causal rounds and the diag/full branch split."""
    n_sp = 4
    B, H, S, D = 1, 2, 32, 16
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:n_sp]), ("sp",))
    f = shard_map(
        functools.partial(
            ring_attention, causal=causal, axis="sp", block_impl="flash"
        ),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = jax.jit(f)(q, k, v)
    ref = default_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # gradients flow through the lse merge, the custom_vjp blocks (dq),
    # the lse-shifted delta (dk/dv), and the reverse-ppermute of the scan
    def loss_ring(q, k, v):
        return jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(default_attention(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_ring_attention_grads_match_dense():
    n_sp = 4
    B, H, S, D = 1, 2, 16, 8
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:n_sp]), ("sp",))

    def ring_loss(q, k, v):
        f = shard_map(
            functools.partial(ring_attention, causal=True, axis="sp"),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
        )
        return jnp.sum(jnp.square(f(q, k, v)))

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(default_attention(q, k, v, causal=True)))

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


# -- sharded trainer vs unsharded reference -------------------------------
PLANS = [
    MeshPlan(dp=1, pp=1, sp=1, tp=1),
    MeshPlan(dp=2, pp=1, sp=2, tp=2),
    MeshPlan(dp=2, pp=2, sp=1, tp=2),
    MeshPlan(dp=1, pp=2, sp=2, tp=2),
    MeshPlan(dp=8, pp=1, sp=1, tp=1),
]


@pytest.mark.parametrize("plan", PLANS, ids=str)
def test_sharded_loss_matches_reference(plan):
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    tparams = model.init(jax.random.PRNGKey(0))
    batch = _batch()
    ref_loss = model.loss(tparams, batch, train=False)

    trainer = ShardedTrainer(cfg, plan, n_micro=2 if plan.pp > 1 else 1)
    params = trainer.from_transformer_params(tparams)
    state = {"params": params, "opt_state": trainer.tx.init(params), "step": 0}
    loss = trainer.loss(state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


@pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
def test_sharded_trainer_schedule_matches_psum():
    """ShardedTrainer(schedule='ring'): the scheduled gradient sync must
    produce the same post-step params as the default psum path on a
    hierarchical dp×sp×tp mesh."""
    plan = MeshPlan(dp=2, pp=1, sp=2, tp=2)
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    batch = _batch()

    outs = {}
    for sched in ("psum", "ring"):
        # fresh params per run: the donated step consumes buffers that
        # from_transformer_params may share with the source tree
        tparams = model.init(jax.random.PRNGKey(0))
        trainer = ShardedTrainer(cfg, plan, schedule=sched)
        params = trainer.from_transformer_params(tparams)
        state = {"params": params, "opt_state": trainer.tx.init(params),
                 "step": 0}
        state, loss = trainer.step(state, batch)
        assert np.isfinite(float(loss))
        outs[sched] = state["params"]
    for a, b in zip(jax.tree_util.tree_leaves(outs["psum"]),
                    jax.tree_util.tree_leaves(outs["ring"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_sharded_loss_fused_xent_matches(monkeypatch):
    """KF_TPU_XENT=fused routes the sharded head through the Pallas
    kernel (interpret mode off-TPU); the loss must match the plain
    log_softmax path — both per-stage masking and the mean reduction."""
    from kungfu_tpu.ops.pallas.xent import XENT_ENV

    monkeypatch.setenv("KF_TPU_XENT", "fused")
    XENT_ENV.reload()
    plan = MeshPlan(dp=2, pp=2, sp=1, tp=2)
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    tparams = model.init(jax.random.PRNGKey(0))
    batch = _batch()
    monkeypatch.setenv("KF_TPU_XENT", "plain")
    XENT_ENV.reload()
    ref_loss = model.loss(tparams, batch, train=False)
    monkeypatch.setenv("KF_TPU_XENT", "fused")
    XENT_ENV.reload()

    trainer = ShardedTrainer(cfg, plan, n_micro=2)
    params = trainer.from_transformer_params(tparams)
    state = {"params": params, "opt_state": trainer.tx.init(params), "step": 0}
    loss = trainer.loss(state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)


@pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
@pytest.mark.parametrize("plan", [MeshPlan(dp=2, pp=1, sp=2, tp=2),
                                  MeshPlan(dp=2, pp=2, sp=1, tp=2)], ids=str)
def test_sharded_step_matches_reference(plan):
    """One SGD step under full sharding must produce the same params as the
    single-device step — validates every gradient-sync path."""
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    tparams = model.init(jax.random.PRNGKey(0))
    batch = _batch()

    lr = 0.05
    ref_grads = jax.grad(lambda p: model.loss(p, batch, train=False))(tparams)
    ref_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, tparams, ref_grads)

    trainer = ShardedTrainer(
        cfg, plan, tx=optax.sgd(lr), n_micro=2 if plan.pp > 1 else 1
    )
    params = trainer.from_transformer_params(tparams)
    state = {"params": params, "opt_state": trainer.tx.init(params),
             "step": jnp.zeros((), jnp.int32)}
    state, _ = trainer.step(state, batch)

    got = jax.device_get(state["params"])
    np.testing.assert_allclose(
        got["embed"]["table"], np.asarray(ref_params["embed"]["table"]),
        rtol=2e-4, atol=2e-5,
    )
    np.testing.assert_allclose(
        got["head"]["w"], np.asarray(ref_params["head"]["w"]), rtol=2e-4, atol=2e-5
    )
    for i in range(cfg.n_layers):
        np.testing.assert_allclose(
            got["layers"]["wq"]["w"][i],
            np.asarray(ref_params[f"layer_{i}"]["wq"]["w"]),
            rtol=2e-4, atol=2e-5,
        )
        np.testing.assert_allclose(
            got["layers"]["ffn_out"]["w"][i],
            np.asarray(ref_params[f"layer_{i}"]["ffn_out"]["w"]),
            rtol=2e-4, atol=2e-5,
        )
        np.testing.assert_allclose(
            got["layers"]["ln1"]["scale"][i],
            np.asarray(ref_params[f"layer_{i}"]["ln1"]["scale"]),
            rtol=2e-4, atol=2e-5,
        )


# -- MoE / expert parallelism ---------------------------------------------
def test_moe_ep_matches_local():
    """Token outputs with experts sharded over ep=2 equal the unsharded
    routing (capacity high enough that nothing drops)."""
    E, D, F, T = 4, 16, 32, 24
    params = moe_init(jax.random.PRNGKey(0), E, D, F, n_experts_global=E)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((T, D)), dtype=jnp.float32)

    y_ref, aux_ref = moe_apply(params, x, axis=None, n_experts_global=E,
                               capacity_factor=float(E))
    assert np.isfinite(float(aux_ref))

    ep = 2
    mesh = Mesh(np.array(jax.devices()[:ep]), ("ep",))
    # shard experts over ep; every rank routes its own half of the tokens
    lparams_spec = {"gate": {"w": P(None, None)}, "w_in": P("ep", None, None),
                    "w_out": P("ep", None, None)}

    def f(lp, xl):
        y, aux = moe_apply(lp, xl, axis="ep", n_experts_global=E,
                           capacity_factor=float(E))
        return y, jax.lax.pmean(aux, "ep")

    g = shard_map(f, mesh=mesh, in_specs=(lparams_spec, P("ep", None)),
                  out_specs=(P("ep", None), P()), check_vma=False)
    y_ep, aux_ep = jax.jit(g)(params, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref), rtol=2e-5, atol=2e-5)
    assert np.isfinite(float(aux_ep))


@pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
def test_moe_trainer_trains():
    """Full 4-D trainer with MoE FFNs: loss decreases on a repeated batch."""
    cfg = TransformerConfig(**CFG)
    plan = MeshPlan(dp=2, pp=1, sp=2, tp=2)
    trainer = ShardedTrainer(cfg, plan, n_experts=4, tx=optax.adam(1e-3),
                             capacity_factor=4.0)
    state = trainer.init(jax.random.PRNGKey(0))
    batch = _batch()
    losses = []
    for _ in range(4):
        state, loss = trainer.step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pipeline_microbatch_counts():
    """Loss is independent of the number of microbatches."""
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    tparams = model.init(jax.random.PRNGKey(0))
    batch = _batch()
    ref = float(model.loss(tparams, batch, train=False))
    for n_micro in (2, 4):
        plan = MeshPlan(dp=1, pp=2, sp=1, tp=1)
        trainer = ShardedTrainer(cfg, plan, n_micro=n_micro)
        params = trainer.from_transformer_params(tparams)
        state = {"params": params, "opt_state": trainer.tx.init(params), "step": 0}
        assert float(trainer.loss(state, batch)) == pytest.approx(ref, rel=1e-5)


@pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
def test_graft_entry_dryrun():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_sync_batchnorm_matches_big_batch():
    """BN with axis_name over a dp mesh must equal single-device BN on
    the concatenated batch — both the normalized output and the running
    stats (the whole point of sync-BN; a per-shard-stats bug converges
    differently at scale and is invisible to loss-goes-down tests)."""
    from kungfu_tpu.models import nn as knn

    n_dp = 4
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((8, 6, 6, 3)), jnp.float32)
    p = knn.batchnorm_init(3)
    st = knn.batchnorm_state_init(3)

    ref_y, ref_stats = knn.batchnorm_apply(p, st, x, train=True)

    mesh = Mesh(np.array(jax.devices()[:n_dp]), ("dp",))
    f = shard_map(
        lambda xs: knn.batchnorm_apply(p, st, xs, train=True, axis_name="dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=(P("dp"), P()),
    )
    y, stats = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y), rtol=2e-5, atol=2e-5)
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(stats[k]), np.asarray(ref_stats[k]), rtol=2e-5, atol=2e-5,
            err_msg=f"running {k} diverged from big-batch BN",
        )


class TestDPTrainStep:
    """dp_train_step: the DP-only helper over a Communicator mesh."""

    def _setup(self):
        from kungfu_tpu.comm.device import Communicator

        comm = Communicator()
        w_true = jnp.asarray([1.0, -2.0, 0.5, 3.0])
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.standard_normal((64, 4)), jnp.float32)
        Y = X @ w_true

        def loss_fn(params, batch):
            x, y = batch
            return jnp.mean((x @ params["w"] - y) ** 2)

        return comm, {"w": jnp.zeros(4)}, loss_fn, (X, Y)

    def test_sync_sgd_replicated_converges(self):
        from kungfu_tpu.optimizers import synchronous_sgd
        from kungfu_tpu.parallel.train import dp_train_step

        comm, params, loss_fn, batch = self._setup()
        tx = synchronous_sgd(optax.sgd(0.1), comm.axis)
        step = dp_train_step(loss_fn, tx, comm)
        state = tx.init(params)
        for _ in range(60):
            params, state, loss = step(params, state, batch)
        assert float(loss) < 1e-2

    def test_sync_sgd_equals_serial_large_batch(self):
        from kungfu_tpu.optimizers import synchronous_sgd
        from kungfu_tpu.parallel.train import dp_train_step

        comm, params, loss_fn, batch = self._setup()
        tx = synchronous_sgd(optax.sgd(0.05), comm.axis)
        step = dp_train_step(loss_fn, tx, comm)
        state = tx.init(params)
        p_dist, _, _ = step(params, state, batch)

        # serial reference: plain SGD on the mean of per-shard mean grads
        n = comm.size
        shards = [
            (batch[0][i * (64 // n):(i + 1) * (64 // n)],
             batch[1][i * (64 // n):(i + 1) * (64 // n)])
            for i in range(n)
        ]
        g = jax.tree_util.tree_map(
            lambda *gs: sum(gs) / n,
            *[jax.grad(loss_fn)(params, s) for s in shards],
        )
        p_ref = jax.tree_util.tree_map(lambda p, g_: p - 0.05 * g_, params, g)
        np.testing.assert_allclose(p_dist["w"], p_ref["w"], rtol=1e-5)

    def test_sma_stacked_replicas_diverge_then_track(self):
        from kungfu_tpu.optimizers import synchronous_averaging
        from kungfu_tpu.parallel.train import dp_train_step, stack_for_replicas

        comm, params, loss_fn, batch = self._setup()
        n = comm.size
        tx = synchronous_averaging(optax.sgd(0.05), comm.axis, alpha=0.2)
        step = dp_train_step(loss_fn, tx, comm, replicated_params=False)
        sp = stack_for_replicas(params, n)
        ss = stack_for_replicas(tx.init(params), n)
        for _ in range(40):
            sp, ss, loss = step(sp, ss, batch)
        assert float(loss) < 0.1
        # replicas stay near each other (pulled toward the average)
        w = np.asarray(sp["w"])
        assert np.max(np.std(w, axis=0)) < 0.2


@pytest.mark.parametrize("plan", [MeshPlan(dp=2, pp=1, sp=2, tp=2),
                                  MeshPlan(dp=2, pp=2, sp=1, tp=2)], ids=str)
def test_sharded_loss_learned_positions_matches(plan):
    """Learned (absolute) positions under full sharding: the pos_embed
    table rides the replicated layout, the lookup uses sp-global
    offsets, and the loss matches the unsharded model."""
    cfg = TransformerConfig(**{**CFG, "pos": "learned"})
    model = Transformer(cfg)
    tparams = model.init(jax.random.PRNGKey(0))
    batch = _batch()
    ref_loss = model.loss(tparams, batch, train=False)

    trainer = ShardedTrainer(cfg, plan, n_micro=2 if plan.pp > 1 else 1)
    params = trainer.from_transformer_params(tparams)
    assert "pos_embed" in params
    state = {"params": params, "opt_state": trainer.tx.init(params), "step": 0}
    loss = trainer.loss(state, batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_sharded_init_learned_positions():
    cfg = TransformerConfig(**{**CFG, "pos": "learned"})
    trainer = ShardedTrainer(cfg, MeshPlan(dp=2, pp=1, sp=1, tp=1))
    state = trainer.init(jax.random.PRNGKey(1))
    pe = state["params"]["pos_embed"]["table"]
    assert pe.shape == (cfg.max_seq, cfg.d_model)
    s, loss = trainer.step(state, _batch())
    assert np.isfinite(float(loss))


def test_sharded_fused_grad_sync_matches():
    """fuse_grads=True (one collective per sync-kind) must produce the
    same post-step params as the per-leaf sync on a hierarchical mesh,
    MoE expert grads included."""
    plan = MeshPlan(dp=2, pp=1, sp=2, tp=2)
    cfg = TransformerConfig(**CFG)
    model = Transformer(cfg)
    batch = _batch()

    outs = {}
    for fused in (False, True):
        tparams = model.init(jax.random.PRNGKey(0))
        trainer = ShardedTrainer(cfg, plan, tx=optax.sgd(0.05),
                                 fuse_grads=fused)
        params = trainer.from_transformer_params(tparams)
        state = {"params": params, "opt_state": trainer.tx.init(params),
                 "step": 0}
        state, loss = trainer.step(state, batch)
        assert np.isfinite(float(loss))
        outs[fused] = state["params"]
    for a, b in zip(jax.tree_util.tree_leaves(outs[False]),
                    jax.tree_util.tree_leaves(outs[True])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_sharded_fused_grad_sync_moe():
    """The 'expert' sync-kind rides the bucketed path too: fused and
    per-leaf sync must produce identical post-step params for an MoE
    trainer (same init, same batch)."""
    plan = MeshPlan(dp=2, pp=1, sp=1, tp=2)
    cfg = TransformerConfig(**CFG)
    batch = _batch()
    outs = {}
    for fused in (False, True):
        trainer = ShardedTrainer(cfg, plan, n_experts=2,
                                 tx=optax.sgd(0.05), fuse_grads=fused)
        state = trainer.init(jax.random.PRNGKey(1))
        state, loss = trainer.step(state, batch)
        assert np.isfinite(float(loss))
        outs[fused] = state["params"]
    for a, b in zip(jax.tree_util.tree_leaves(outs[False]),
                    jax.tree_util.tree_leaves(outs[True])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
