"""Elasticity tests: config server REST contract, schedules, resize
protocol (reference test_step_based_schedule.py / test_tensorflow_resize.py
/ run-elastic-test.sh analogs)."""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from kungfu_tpu.elastic import ConfigServer, parse_schedule, step_based_schedule
from kungfu_tpu.elastic.schedule import total_steps
from kungfu_tpu.plan import Cluster, HostList

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cluster(np=2):
    hl = HostList.parse("127.0.0.1:8")
    return Cluster(hl.gen_runner_list(), hl.gen_peer_list(np))


class TestSchedule:
    def test_parse(self):
        assert parse_schedule("1:100,2:50") == [(1, 100), (2, 50)]
        assert total_steps("1:100,2:50") == 150

    @pytest.mark.parametrize("step,size", [(0, 1), (99, 1), (100, 2), (149, 2), (500, 4)])
    def test_lookup(self, step, size):
        assert step_based_schedule("1:100,2:50,4:10", step) == size

    def test_bad(self):
        with pytest.raises(ValueError):
            parse_schedule("0:10")
        with pytest.raises(ValueError):
            parse_schedule("")


class TestConfigServer:
    @pytest.fixture
    def server(self):
        s = ConfigServer(port=29100, cluster=make_cluster(2)).start()
        yield s
        try:
            s.stop()
        except Exception:
            pass

    def _get(self, port, path="/get"):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return json.loads(r.read().decode())

    def _put(self, port, body: str):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/put", data=body.encode(), method="PUT"
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return json.loads(r.read().decode())

    def test_get_put_versioning(self, server):
        doc = self._get(29100)
        assert doc["version"] == 0
        assert len(doc["cluster"]["workers"]) == 2
        new = make_cluster(4)
        out = self._put(29100, new.to_json())
        assert out["version"] == 1
        doc = self._get(29100)
        assert doc["version"] == 1 and len(doc["cluster"]["workers"]) == 4

    def test_put_invalid_rejected(self, server):
        bad = json.dumps({"runners": ["a:38080"], "workers": ["b:10000"]})
        with pytest.raises(urllib.error.HTTPError) as e:
            self._put(29100, bad)
        assert e.value.code == 400
        assert self._get(29100)["version"] == 0  # unchanged

    def test_delete_then_404(self, server):
        req = urllib.request.Request("http://127.0.0.1:29100/", method="DELETE")
        urllib.request.urlopen(req, timeout=5).read()
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(29100)
        assert e.value.code == 404


class TestResizeProtocol:
    def test_fetch_with_consensus_two_peers(self):
        from kungfu_tpu.elastic.resize import fetch_cluster_with_consensus
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import PeerList
        from kungfu_tpu.utils.envs import Config

        server = ConfigServer(port=29101, cluster=make_cluster(2)).start()
        try:
            workers = PeerList.parse("127.0.0.1:26001,127.0.0.1:26002")
            runners = PeerList.parse("127.0.0.1:38085")
            cluster = Cluster(runners, workers)
            peers = [
                Peer(Config(self_id=workers[i], cluster=cluster,
                            config_server="http://127.0.0.1:29101/get"))
                for i in range(2)
            ]
            for p in peers:
                p.start()
            results = [None, None]

            def fetch(i):
                results[i] = fetch_cluster_with_consensus(peers[i], timeout=30)

            ts = [threading.Thread(target=fetch, args=(i,)) for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=40)
            assert results[0] is not None and results[1] is not None
            assert results[0][1] == results[1][1] == 0
            assert results[0][0] == results[1][0]
            for p in peers:
                p.close()
        finally:
            server.stop()


@pytest.mark.slow
class TestElasticCLI:
    def _run(self, schedule, np, port):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        return subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli", "-w",
             "-builtin-config-port", str(port), "-np", str(np),
             "-H", "127.0.0.1:4", sys.executable,
             "examples/elastic_mnist.py", "--schedule", schedule],
            cwd=REPO, capture_output=True, text=True, timeout=280, env=env,
        )

    def test_grow(self):
        r = self._run("1:4,2:4", 1, 29125)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "resizes survived 1" in r.stdout

    def test_shrink(self):
        r = self._run("2:4,1:4", 2, 29126)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "sizes seen [1, 2]" in r.stdout


def test_watch_natural_end_probes_config_server():
    """The natural-end grace check asks the config server whether a
    resize stage is in flight (version ahead of the runner's) before
    concluding the job ended — a runner exiting early orphans its host
    for every later re-grow."""
    from kungfu_tpu.elastic.configserver import ConfigServer
    from kungfu_tpu.plan import Cluster, PeerList
    from kungfu_tpu.runner.watch import _config_server_version

    cluster = Cluster(PeerList.parse("127.0.0.1:38071"),
                      PeerList.parse("127.0.0.1:24061"))
    srv = ConfigServer(port=0, cluster=cluster).start()
    try:
        url = srv.url
        assert _config_server_version(url) == 0
        # a PUT bumps the version: the runner (still at v0) must see it
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            url.replace("/get", "/put"),
            data=cluster.to_json().encode(), method="PUT")
        with urllib.request.urlopen(req, timeout=5):
            pass
        assert _config_server_version(url) == 1
    finally:
        srv.stop()
    # unreachable server -> None (callers fall back to the grace timeout)
    assert _config_server_version("http://127.0.0.1:9/get") is None
    assert _config_server_version("") is None
