"""Pallas ICI ring collectives — the interpreter-path bitwise suite.

The contract (docs/pallas_collectives.md), pinned form by form:

* every kernel (uni/bidirectional reduce-scatter and all-gather, the
  1-chunk and padded-tail degenerate shapes, non-divisible world sizes)
  is **bitwise-identical** on the CPU interpreter path to the
  order-matched lax emulation — same hop schedule, same fold-operand
  order, so the float bits cannot differ;
* against the ``lax.psum_scatter`` / ``lax.all_gather`` reference:
  all-gather is pure data movement and pins bitwise unconditionally;
  reduce-scatter pins bitwise on order-exact data (ints, integer-valued
  floats) and allclose on arbitrary floats (the ring's reduction order
  is documented, not XLA's);
* the custom-vjp pair: grad through the all-gather IS the ring
  reduce-scatter of the cotangent (and vice versa), impl-bitwise;
* the ``pallas_ring`` schedule plumbs through ``reduce_scatter_flat`` /
  ``all_gather_flat`` (bucketing bitwise-invariant, ZeRO geometry
  byte-identical), the eager ``Communicator`` per-bucket table, the
  ZeRO-2/3 step, ring attention's gathered-K/V path, and the sharded
  trainer's gradient sync.

This file is the ``make pallas-check`` gate (scripts/check.sh).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from kungfu_tpu.ops.pallas.collectives import (
    ring_all_gather,
    ring_all_reduce,
    ring_reduce_scatter,
    ring_wire_bytes,
)
from kungfu_tpu.utils.jaxcompat import shard_map

N_DEV = 8


def _world(n, fn, x, out_specs=None):
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("x",))
    f = shard_map(fn, mesh=mesh, in_specs=(P("x"),),
                  out_specs=out_specs if out_specs is not None else P("x"),
                  check_vma=False)
    return np.asarray(jax.jit(f)(x))


# world sizes: even rings, odd/non-divisible rings, the 2-ring edge
WORLDS = (2, 3, 5, 8)
# chunk shapes: a 2-band chunk where the bidirectional row split really
# engages (f32 needs >= 16 rows, i.e. chunk > 1024 — anything shorter
# falls back to unidirectional), a full single-tile chunk, a ragged
# (padded-tail) chunk, and the 1-chunk degenerate (smaller than one
# [8, 128] tile)
CHUNKS = (2048, 1024, 1000, 40)


def test_band_split_engages_in_this_suite():
    """Guard the guard: _band_rows must actually split at least one
    CHUNKS entry, or every ``bidi=True`` parametrization silently tests
    the unidirectional fallback twice (the exact gap a review caught:
    chunk 1024 is 8 f32 rows — below the 2-sublane-tile threshold)."""
    from kungfu_tpu.ops.pallas.collectives import _band_rows, _tile_rows

    assert _band_rows(8, np.float32) == 0        # uni fallback
    assert _band_rows(16, np.float32) == 8       # 8/8 split
    assert _band_rows(24, np.float32) == 16      # 16/8 split
    split = [c for c in CHUNKS
             if _band_rows(_tile_rows(c, np.float32), np.float32) > 0]
    assert split, "no CHUNKS entry engages the bidirectional band split"


class TestReduceScatterBitwise:
    @pytest.mark.parametrize("n", WORLDS)
    @pytest.mark.parametrize("chunk", CHUNKS)
    @pytest.mark.parametrize("bidi", [False, True])
    def test_kernel_bitwise_vs_emulation_and_close_vs_lax(
            self, n, chunk, bidi):
        rng = np.random.default_rng(n * 7919 + chunk + bidi)
        x = rng.standard_normal((n, n * chunk)).astype(np.float32)

        def rs(impl):
            body = lambda row: ring_reduce_scatter(
                row[0], "x", bidirectional=bidi, impl=impl)[None]
            return _world(n, body, jnp.asarray(x)).reshape(n, chunk)

        kern, emul = rs("pallas"), rs("lax")
        assert kern.tobytes() == emul.tobytes(), (
            f"kernel != emulation (n={n} chunk={chunk} bidi={bidi})")
        # the lax reference: psum_scatter of the same mesh-major buffer
        def ref_body(row):
            return jax.lax.psum_scatter(
                row[0], "x", scatter_dimension=0, tiled=True)[None]

        ref = _world(n, ref_body, jnp.asarray(x)).reshape(n, chunk)
        np.testing.assert_allclose(kern, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("n", (3, 8))
    @pytest.mark.parametrize("bidi", [False, True])
    @pytest.mark.parametrize("dtype", [np.float32, np.int32])
    def test_kernel_bitwise_vs_psum_scatter_on_exact_data(
            self, n, bidi, dtype):
        """Order-exact data (int32, and integer-valued f32 whose sums
        are exactly representable): EVERY reduction order produces the
        same bits, so the kernel pins bitwise against the
        lax.psum_scatter reference itself."""
        chunk = 200
        rng = np.random.default_rng(11 + n)
        x = rng.integers(-1000, 1000, (n, n * chunk)).astype(dtype)

        def rs(row):
            return ring_reduce_scatter(
                row[0], "x", bidirectional=bidi, impl="pallas")[None]

        def ref(row):
            return jax.lax.psum_scatter(
                row[0], "x", scatter_dimension=0, tiled=True)[None]

        got = _world(n, rs, jnp.asarray(x))
        want = _world(n, ref, jnp.asarray(x))
        assert got.tobytes() == want.tobytes()

    @pytest.mark.parametrize("chunk,bidi", [
        (400, False),
        # bf16 sublane is 16 rows: the band split needs >= 32 rows,
        # i.e. chunk > 3968 — 4096 really exercises the bf16 bands
        (4096, True),
    ])
    def test_bf16_bitwise_vs_emulation(self, chunk, bidi):
        from kungfu_tpu.ops.pallas.collectives import (_band_rows,
                                                       _tile_rows)

        n = 4
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((n, n * chunk)), jnp.bfloat16)
        if bidi:
            assert _band_rows(_tile_rows(chunk, jnp.bfloat16),
                              jnp.bfloat16) > 0

        def rs(impl):
            body = lambda row: ring_reduce_scatter(
                row[0], "x", bidirectional=bidi, impl=impl)[None]
            return _world(n, body, x)

        assert rs("pallas").tobytes() == rs("lax").tobytes()

    def test_single_device_identity(self):
        x = jnp.arange(12, dtype=jnp.float32)
        got = _world(1, lambda row: ring_reduce_scatter(
            row[0], "x", impl="pallas")[None], x[None])
        np.testing.assert_array_equal(got[0], np.asarray(x))

    def test_rejects_non_divisible_buffer(self):
        with pytest.raises(ValueError, match="flat"):
            _world(2, lambda row: ring_reduce_scatter(
                row[0], "x", impl="lax")[None],
                jnp.ones((2, 7), jnp.float32))


class TestAllGatherBitwise:
    @pytest.mark.parametrize("n", WORLDS)
    @pytest.mark.parametrize("chunk", CHUNKS)
    @pytest.mark.parametrize("bidi", [False, True])
    def test_kernel_bitwise_vs_emulation_and_lax(self, n, chunk, bidi):
        """Gathering is pure movement: kernel == emulation == the
        lax.all_gather reference, all bitwise."""
        rng = np.random.default_rng(n * 131 + chunk + bidi)
        shards = rng.standard_normal((n, chunk)).astype(np.float32)

        def ag(impl):
            body = lambda s: ring_all_gather(
                s[0], "x", bidirectional=bidi, impl=impl)[None]
            return _world(n, body, jnp.asarray(shards))

        def ref(s):
            return jax.lax.all_gather(s[0], "x", axis=0, tiled=True)[None]

        kern, emul = ag("pallas"), ag("lax")
        want = _world(n, ref, jnp.asarray(shards))
        assert kern.tobytes() == emul.tobytes()
        assert kern.tobytes() == want.tobytes()

    def test_int32_and_single_device(self):
        n, chunk = 3, 70
        x = np.arange(n * chunk, dtype=np.int32).reshape(n, chunk)
        got = _world(n, lambda s: ring_all_gather(
            s[0], "x", impl="pallas")[None], jnp.asarray(x))
        assert got.reshape(n, n * chunk).tobytes() == np.tile(
            x.reshape(-1), (n, 1)).tobytes()
        y = jnp.arange(5, dtype=jnp.float32)
        got1 = _world(1, lambda s: ring_all_gather(
            s[0], "x", impl="pallas")[None], y[None])
        np.testing.assert_array_equal(got1[0], np.asarray(y))


class TestVjpPair:
    """The custom-vjp contract: gather's backward IS the ring
    reduce-scatter (ZeRO-3's transpose invariant), scatter's backward is
    the gather — and the kernel/emulation pair agrees bitwise on
    gradients too."""

    @pytest.mark.parametrize("bidi", [False, True])
    def test_gather_grad_is_reduce_scatter(self, bidi):
        n, chunk = 4, 300
        rng = np.random.default_rng(2)
        shards = rng.standard_normal((n, chunk)).astype(np.float32)
        w = rng.standard_normal((n * chunk,)).astype(np.float32)

        def grad_of(impl):
            def body(s):
                def loss(sh):
                    full = ring_all_gather(
                        sh, "x", bidirectional=bidi, impl=impl)
                    return jnp.sum(full * w) * jnp.ones((1,))

                return jax.grad(lambda sh: loss(sh)[0])(s[0])[None]

            return _world(n, body, jnp.asarray(shards))

        kern, emul = grad_of("pallas"), grad_of("lax")
        assert kern.tobytes() == emul.tobytes()
        # every device's cotangent is w → the shard grad is the
        # reduce-scatter of n identical copies: n * w[chunk r]
        np.testing.assert_allclose(
            kern.reshape(n, chunk), w.reshape(n, chunk) * n, rtol=1e-4)

    def test_scatter_grad_is_gather(self):
        n, chunk = 4, 128
        rng = np.random.default_rng(3)
        flat = rng.standard_normal((n, n * chunk)).astype(np.float32)

        def grad_of(impl):
            def body(s):
                def loss(f):
                    red = ring_reduce_scatter(f, "x", impl=impl)
                    return jnp.sum(red ** 2) * jnp.ones((1,))

                return jax.grad(lambda f: loss(f)[0])(s[0])[None]

            return _world(n, body, jnp.asarray(flat))

        kern, emul = grad_of("pallas"), grad_of("lax")
        assert kern.tobytes() == emul.tobytes()


class TestWireParity:
    """Traced-bytes parity: the emulation's explicit ppermute hops cost
    exactly what the lax reference primitives cost under the standard
    ring convention — the program the schedule claims is the program it
    moves."""

    def test_emulation_bytes_match_reference_costs(self):
        from kungfu_tpu.ops.schedules import traced_collective_bytes

        # chunk = one exact [8, 128] f32 tile: sub-tile chunks pad up to
        # tile granularity ON THE WIRE too (documented overhead; real
        # buckets are orders of magnitude above a tile)
        n, chunk = 8, 1024
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("x",))

        def rs_emul(row):
            return ring_reduce_scatter(row[0], "x", impl="lax")[None]

        def ag_emul(s):
            return ring_all_gather(s[0], "x", impl="lax")[None]

        rs = traced_collective_bytes(
            shard_map(rs_emul, mesh=mesh, in_specs=(P("x"),),
                      out_specs=P("x")),
            jnp.ones((n, n * chunk), jnp.float32), axis_sizes={"x": n})
        ag = traced_collective_bytes(
            shard_map(ag_emul, mesh=mesh, in_specs=(P("x"),),
                      out_specs=P("x")),
            jnp.ones((n, chunk), jnp.float32), axis_sizes={"x": n})
        buf = n * chunk * 4
        assert rs == {"ppermute": pytest.approx(
            ring_wire_bytes(buf, n, "reduce_scatter"))}
        assert ag == {"ppermute": pytest.approx(
            ring_wire_bytes(chunk * 4, n, "all_gather"))}

    def test_analytic_matches_schedule_table(self):
        from kungfu_tpu.ops.schedules import _COLLECTIVE_COST

        for n in (2, 3, 8):
            s = 4096.0
            assert ring_wire_bytes(s, n, "reduce_scatter") == (
                _COLLECTIVE_COST["reduce_scatter"](s, n))
            assert ring_wire_bytes(s, n, "all_gather") == (
                _COLLECTIVE_COST["all_gather"](s, n))
            assert ring_wire_bytes(s, n, "all_reduce") == (
                _COLLECTIVE_COST["psum"](s, n))
        with pytest.raises(ValueError, match="unknown kind"):
            ring_wire_bytes(1, 2, "gossip")


class TestScheduleIntegration:
    """pallas_ring as a first-class member of the schedule layer."""

    def test_registered_in_allreduce_schedules(self):
        from kungfu_tpu.ops.schedules import (ALLREDUCE_SCHEDULES,
                                              FLAT_SCHEDULES)

        assert "pallas_ring" in ALLREDUCE_SCHEDULES
        assert FLAT_SCHEDULES == ("lax", "pallas_ring")

    @pytest.mark.parametrize("op", ["sum", "mean", "min", "max"])
    def test_all_reduce_scheduled_matches_psum(self, op):
        from kungfu_tpu.ops.schedules import all_reduce_scheduled

        rng = np.random.default_rng(4)
        x = rng.standard_normal((N_DEV, 37)).astype(np.float32)

        def body(s):
            return all_reduce_scheduled(s, "x", op=op,
                                        schedule="pallas_ring")

        got = _world(N_DEV, body, jnp.asarray(x))
        ref = {"sum": np.sum, "mean": np.mean, "min": np.min,
               "max": np.max}[op](x.astype(np.float64), axis=0)
        np.testing.assert_allclose(got, np.broadcast_to(ref, x.shape),
                                   rtol=1e-5, atol=1e-5)

    def test_hierarchical_tuple_axes(self):
        """(host, local) axis tuples: inner folds by psum, the ring
        kernels run the cross-host stage — same contract as ring/two_stage."""
        from kungfu_tpu.ops.schedules import all_reduce_scheduled

        mesh = Mesh(np.asarray(jax.devices()[:N_DEV]).reshape(2, 4),
                    ("h", "l"))
        rng = np.random.default_rng(5)
        x = rng.standard_normal((N_DEV, 21)).astype(np.float32)

        def body(s):
            return all_reduce_scheduled(s, ("h", "l"), op="mean",
                                        schedule="pallas_ring")

        f = shard_map(body, mesh=mesh, in_specs=(P(("h", "l")),),
                      out_specs=P(("h", "l")))
        got = np.asarray(jax.jit(f)(jnp.asarray(x)))
        np.testing.assert_allclose(
            got, np.broadcast_to(x.mean(0), x.shape), rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("widths", [None, [5], [2, 3], [4, 1], [1] * 5])
    def test_flat_bucketing_bitwise_invariant(self, widths):
        """Bucketing is pure program structure under pallas_ring too:
        any bucket layout produces the same bits (the ZeRO invariant)."""
        from kungfu_tpu.ops.schedules import reduce_scatter_flat

        n, chunk = 8, 5
        rng = np.random.default_rng(6)
        x = rng.standard_normal((n, n * chunk)).astype(np.float32)

        def run(w):
            body = lambda row: reduce_scatter_flat(
                row[0], ["x"], chunk, w, schedule="pallas_ring")[None]
            return _world(n, body, jnp.asarray(x))

        assert run(widths).tobytes() == run(None).tobytes()

    def test_flat_gather_bitwise_vs_lax_and_roundtrip(self):
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              reduce_scatter_flat)

        n, chunk = 8, 6
        rng = np.random.default_rng(7)
        x = rng.standard_normal((n, n * chunk)).astype(np.float32)

        def round_trip(schedule):
            def body(row):
                shard = reduce_scatter_flat(row[0], ["x"], chunk, [4, 2],
                                            schedule=schedule)
                return all_gather_flat(shard, ["x"], [4, 2],
                                       schedule=schedule)[None]

            return _world(n, body, jnp.asarray(x))

        got = round_trip("pallas_ring")
        np.testing.assert_allclose(
            got.reshape(n, n * chunk),
            np.broadcast_to(x.sum(0), (n, n * chunk)), rtol=1e-4)
        # gather alone is movement: bitwise across schedules
        shards = rng.standard_normal((n, chunk)).astype(np.float32)

        def gather(schedule):
            body = lambda s: all_gather_flat(
                s[0], ["x"], schedule=schedule)[None]
            return _world(n, body, jnp.asarray(shards))

        assert gather("pallas_ring").tobytes() == gather("lax").tobytes()

    def test_unknown_schedule_rejected(self):
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              reduce_scatter_flat)

        with pytest.raises(ValueError, match="unknown flat schedule"):
            reduce_scatter_flat(jnp.ones(8), ["x"], 2, schedule="bogus")
        with pytest.raises(ValueError, match="unknown flat schedule"):
            all_gather_flat(jnp.ones(8), ["x"], schedule="bogus")


class TestCommunicatorIntegration:
    """The eager device plane: pallas_ring installed per payload bucket
    routes the stacked collectives through the ring schedules."""

    def _comm(self):
        from kungfu_tpu.comm.device import Communicator

        return Communicator(devices=jax.devices()[:4], local_size=4)

    def test_all_reduce_under_pallas_ring_strategy(self):
        comm = self._comm()
        comm.set_strategy("pallas_ring")
        rng = np.random.default_rng(8)
        x = rng.standard_normal((4, 33)).astype(np.float32)
        for op in ("sum", "mean", "max"):
            got = np.asarray(comm.all_reduce(jnp.asarray(x), op=op))
            ref = {"sum": np.sum, "mean": np.mean, "max": np.max}[op](
                x.astype(np.float64), axis=0)
            np.testing.assert_allclose(
                got, np.broadcast_to(ref, x.shape), rtol=1e-5, atol=1e-5)

    def test_bucketed_scatter_gather_roundtrip(self):
        from kungfu_tpu.ops.schedules import size_bucket

        comm = self._comm()
        rng = np.random.default_rng(9)
        x = rng.standard_normal((4, 1000)).astype(np.float32)
        bucket = size_bucket(x[0].nbytes)
        comm.set_bucket_strategy(bucket, "pallas_ring")
        red = comm.reduce_scatter(jnp.asarray(x))
        back = comm.all_gather_shard(red)
        full = np.asarray(back)[0]
        np.testing.assert_allclose(full, x.sum(0), rtol=1e-4, atol=1e-5)
        # the compiled program is cached under the schedule key: clearing
        # the override swaps back to a DIFFERENT cached program
        n_fns = len(comm._fns)
        comm.set_bucket_strategy(bucket, None)
        comm.reduce_scatter(jnp.asarray(x))
        assert len(comm._fns) == n_fns + 1


class TestZeroIntegration:
    """ZeRO-2/3 bucket loops riding schedule="pallas_ring": same losses
    and params as the lax schedule (allclose — the ring's documented
    reduction order), same shard geometry (bitwise)."""

    def _setup(self, stage, schedule):
        import optax

        from kungfu_tpu.comm.device import Communicator
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = Communicator(devices=jax.devices()[:4], local_size=4)

        def loss_fn(params, batch):
            x, y = batch
            pred = x @ params["w"] + params["b"]
            return jnp.mean((pred - y) ** 2)

        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(5, 3), jnp.float32),
                  "b": jnp.asarray(rng.randn(3), jnp.float32)}
        batch = (jnp.asarray(rng.randn(8, 5), jnp.float32),
                 jnp.asarray(rng.randn(8, 3), jnp.float32))
        step = zero_train_step(loss_fn, optax.sgd(0.1), comm, stage=stage,
                               bucket_bytes=16, schedule=schedule)
        return step, params, batch

    @pytest.mark.parametrize("stage", [2, 3])
    def test_stage_matches_lax_schedule(self, stage):
        outs = {}
        for schedule in ("lax", "pallas_ring"):
            step, params, batch = self._setup(stage, schedule)
            if stage == 3:
                p = step.init_params(params)
            else:
                p = params
            opt = step.init_opt(params)
            for _ in range(2):
                p, opt, loss = step.step(p, opt, batch)
            if stage == 3:
                p = step.gather_params(p)
            outs[schedule] = (jax.tree_util.tree_map(np.asarray, p),
                              float(loss))
        (p_lax, l_lax), (p_pal, l_pal) = outs["lax"], outs["pallas_ring"]
        np.testing.assert_allclose(l_pal, l_lax, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(p_pal),
                        jax.tree_util.tree_leaves(p_lax)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_bad_schedule_rejected(self):
        import optax

        from kungfu_tpu.comm.device import Communicator
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = Communicator(devices=jax.devices()[:4], local_size=4)
        with pytest.raises(ValueError, match="unknown schedule"):
            zero_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm,
                            schedule="bogus")


class TestRingAttentionIntegration:
    """ring_attention(kv_gather=...): one ring all-gather of K/V instead
    of n ppermute rounds — exact vs the rotation path."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("schedule", ["lax", "pallas_ring"])
    def test_gathered_matches_rotation(self, causal, schedule):
        from kungfu_tpu.parallel.ring import ring_attention

        n_sp, B, H, S, D = 4, 1, 2, 8, 16
        rng = np.random.default_rng(10)
        q, k, v = (jnp.asarray(
            rng.standard_normal((B, H, n_sp * S, D)), jnp.float32)
            for _ in range(3))
        mesh = Mesh(np.asarray(jax.devices()[:n_sp]), ("sp",))

        def run(kv_gather):
            def body(q_, k_, v_):
                return ring_attention(q_, k_, v_, causal=causal,
                                      axis="sp", block_impl="einsum",
                                      kv_gather=kv_gather)

            f = shard_map(body, mesh=mesh,
                          in_specs=(P(None, None, "sp", None),) * 3,
                          out_specs=P(None, None, "sp", None))
            return np.asarray(jax.jit(f)(q, k, v))

        np.testing.assert_allclose(run(schedule), run(None),
                                   rtol=2e-5, atol=2e-5)

    def test_gathered_path_differentiable(self):
        """dK/dV flow back through the gather's transpose (the ring
        reduce-scatter custom vjp) and match the rotation path."""
        from kungfu_tpu.parallel.ring import ring_attention

        n_sp, B, H, S, D = 2, 1, 1, 4, 8
        rng = np.random.default_rng(11)
        q, k, v = (jnp.asarray(
            rng.standard_normal((B, H, n_sp * S, D)), jnp.float32)
            for _ in range(3))
        mesh = Mesh(np.asarray(jax.devices()[:n_sp]), ("sp",))

        def grads(kv_gather):
            def body(q_, k_, v_):
                def loss(kk, vv):
                    out = ring_attention(q_, kk, vv, causal=True,
                                         axis="sp", block_impl="einsum",
                                         kv_gather=kv_gather)
                    return jnp.sum(out ** 2) * jnp.ones((1,))

                g = jax.grad(lambda kk, vv: loss(kk, vv)[0],
                             argnums=(0, 1))(k_, v_)
                return g

            f = shard_map(body, mesh=mesh,
                          in_specs=(P(None, None, "sp", None),) * 3,
                          out_specs=(P(None, None, "sp", None),) * 2)
            return [np.asarray(t) for t in jax.jit(f)(q, k, v)]

        for a, b in zip(grads("pallas_ring"), grads(None)):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)

    def test_bad_kv_gather_rejected(self):
        from kungfu_tpu.parallel.ring import ring_attention

        with pytest.raises(ValueError, match="kv_gather"):
            ring_attention(jnp.ones((1, 1, 4, 8)), jnp.ones((1, 1, 4, 8)),
                           jnp.ones((1, 1, 4, 8)), kv_gather="bogus")


class TestLaunchKnob:
    def test_env_selects_default_impl(self, monkeypatch):
        from kungfu_tpu.ops.pallas import collectives as C

        monkeypatch.setenv("KF_PALLAS_COLLECTIVES", "lax")
        C.ENV.reload()
        assert C._use_pallas(None) is False
        monkeypatch.setenv("KF_PALLAS_COLLECTIVES", "pallas")
        C.ENV.reload()
        assert C._use_pallas(None) is True
        monkeypatch.setenv("KF_PALLAS_COLLECTIVES", "bogus")
        with pytest.raises(ValueError, match="KF_PALLAS_COLLECTIVES"):
            C.ENV.reload()
        monkeypatch.setenv("KF_PALLAS_COLLECTIVES", "auto")
        C.ENV.reload()
        assert C._use_pallas(None) == (jax.default_backend() == "tpu")

    def test_explicit_impl_overrides_env(self):
        from kungfu_tpu.ops.pallas import collectives as C

        assert C._use_pallas("pallas") is True
        assert C._use_pallas("lax") is False
        with pytest.raises(ValueError, match="impl"):
            C._use_pallas("bogus")


class TestShardedTrainerSchedule:
    """The sharded trainer (ring attention + fused LM head inside)
    accepts schedule="pallas_ring" for its gradient sync — the last
    consumer named by ROADMAP item 2."""

    def test_trainer_accepts_pallas_ring(self):
        from kungfu_tpu.models.transformer import TransformerConfig
        from kungfu_tpu.parallel.train import MeshPlan, ShardedTrainer

        cfg = TransformerConfig(vocab_size=64, d_model=16, n_layers=1,
                                n_heads=2, d_ff=32, max_seq=8,
                                dtype="float32")
        trainer = ShardedTrainer(cfg, MeshPlan(dp=2, pp=1, sp=1, tp=1),
                                 schedule="pallas_ring")
        assert trainer.schedule == "pallas_ring"
        with pytest.raises(ValueError, match="unknown schedule"):
            ShardedTrainer(cfg, MeshPlan(dp=2, pp=1, sp=1, tp=1),
                           schedule="bogus")
