"""Failure detection + auto-recovery tests (fork subsystem parity)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from kungfu_tpu.checkpoint import (
    latest_step,
    prune_checkpoints,
    restore_checkpoint,
    save_checkpoint,
)
from kungfu_tpu.monitor.detector import DetectorServer, post_signal
from kungfu_tpu.runner.monitored import find_epochs, parse_period, patch_args

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestArgPatching:
    def test_patch(self):
        args = ["train.py", "--n-epochs", "10", "--lr", "0.1"]
        out = patch_args(args, 7)
        assert out == ["train.py", "--n-epochs", "7", "--lr", "0.1", "--restart", "1"]

    def test_patch_eq_form(self):
        out = patch_args(["t.py", "--n-epochs=10"], 3)
        assert "--n-epochs=3" in out and "--restart" in out

    def test_patch_overrides_explicit_restart_0(self):
        out = patch_args(["t.py", "--n-epochs", "10", "--restart", "0"], 4)
        i = out.index("--restart")
        assert out[i + 1] == "1"
        out = patch_args(["t.py", "--n-epochs=10", "--restart=0"], 4)
        assert "--restart=1" in out and "--restart=0" not in out

    def test_patch_missing_appends(self):
        out = patch_args(["t.py"], 5)
        assert out[-4:] == ["--n-epochs", "5", "--restart", "1"]

    def test_find_epochs(self):
        assert find_epochs(["x", "--n-epochs", "12"]) == 12
        assert find_epochs(["x", "--n-epochs=3"]) == 3
        assert find_epochs(["x"]) is None

    def test_parse_period(self):
        assert parse_period("10s") == 10.0
        assert parse_period("2m") == 120.0
        assert parse_period("500ms") == 0.5
        with pytest.raises(ValueError):
            parse_period("abc")


class TestDetector:
    @pytest.fixture
    def detector(self):
        # compile_grace pinned equal to stall_timeout: these tests
        # simulate steady-state stalls; the compile-aware allowance has
        # its own tests below
        d = DetectorServer(expected_ranks=2, port=27756, stall_timeout=1.0,
                           compile_grace=1.0).start()
        yield d
        d.stop()

    def test_stall_detection(self, detector):
        post_signal("127.0.0.1", 27756, {"kind": "epoch", "rank": 0, "epoch": 0})
        post_signal("127.0.0.1", 27756, {"kind": "epoch", "rank": 1, "epoch": 1})
        post_signal("127.0.0.1", 27756, {"kind": "begin", "rank": 1})
        # rank 1 never sends end -> down after ~1s, min epoch = 1 (rank0 done 1)
        deadline = time.time() + 10
        while not detector.results.down_flag and time.time() < deadline:
            time.sleep(0.2)
        assert detector.results.down_flag
        assert detector.results.epoch_num == 1
        assert detector.min_epoch() == 1

    def test_begin_end_cycle_no_false_positive(self, detector):
        for _ in range(3):
            post_signal("127.0.0.1", 27756, {"kind": "begin", "rank": 0})
            time.sleep(0.1)
            post_signal("127.0.0.1", 27756, {"kind": "end", "rank": 0})
        time.sleep(2.0)
        assert not detector.results.down_flag

    def test_finish_flag(self, detector):
        post_signal("127.0.0.1", 27756, {"kind": "trainend", "rank": 0})
        assert not detector.results.finish_flag  # only 1 of 2 ranks
        post_signal("127.0.0.1", 27756, {"kind": "trainend", "rank": 1})
        assert detector.results.finish_flag

    def test_otherdown_fanout_intake(self, detector):
        post_signal("127.0.0.1", 27756, {"kind": "otherdown", "epoch": 3})
        assert detector.results.down_flag and detector.results.epoch_num == 3

    def test_otherdown_unknown_epoch_uses_local_state(self, detector):
        """epoch=-1 ("sender had no rank state") must fall back to this
        host's own accounting, not restart from epoch 0."""
        post_signal("127.0.0.1", 27756, {"kind": "epoch", "rank": 0, "epoch": 4})
        post_signal("127.0.0.1", 27756, {"kind": "epoch", "rank": 1, "epoch": 5})
        post_signal("127.0.0.1", 27756, {"kind": "otherdown", "epoch": -1})
        assert detector.results.down_flag
        assert detector.results.epoch_num == 5

    def test_report_local_down_without_state_sends_unknown(self, detector):
        """A host that never saw a heartbeat reports epoch 'unknown', and
        its local flag is clamped to 0."""
        detector.report_local_down()
        assert detector.results.down_flag
        assert detector.results.epoch_num == 0

    def test_status_endpoint(self, detector):
        with urllib.request.urlopen("http://127.0.0.1:27756/", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert set(doc) == {"down", "epoch", "finished"}

    def test_reset(self, detector):
        post_signal("127.0.0.1", 27756, {"kind": "otherdown", "epoch": 3})
        detector.reset()
        assert not detector.results.down_flag
        assert detector.min_epoch() == 0


class TestCompileGrace:
    """Slow-compile vs dead-host (SURVEY §7 hard part): the first batch
    and explicitly announced re-jits get the compile allowance, not the
    heartbeat allowance."""

    @pytest.fixture
    def detector(self):
        d = DetectorServer(expected_ranks=1, port=27757, stall_timeout=0.5,
                           compile_grace=2.5).start()
        yield d
        d.stop()

    def test_first_batch_outlasts_stall_timeout(self, detector):
        """begin with no end for > stall_timeout but < compile_grace: a
        cold XLA compile, not a dead rank."""
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        time.sleep(1.2)  # 2.4x the stall timeout
        assert not detector.results.down_flag
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        assert not detector.results.down_flag

    def test_first_batch_grace_is_bounded(self, detector):
        """A rank that never finishes its first batch still goes down —
        after compile_grace instead of stall_timeout."""
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        deadline = time.time() + 10
        while not detector.results.down_flag and time.time() < deadline:
            time.sleep(0.2)
        assert detector.results.down_flag

    def test_steady_state_uses_stall_timeout(self, detector):
        """After one completed batch the allowance drops back."""
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        time.sleep(1.5)  # > stall_timeout, < compile_grace
        assert detector.results.down_flag

    def test_grace_signal_extends_mid_training(self, detector):
        """A resize re-jit announced via the grace signal gets the
        compile allowance even after completed batches."""
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "grace", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        time.sleep(1.2)  # > stall_timeout, inside the grace window
        assert not detector.results.down_flag
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        assert not detector.results.down_flag

    def test_grace_anchors_at_begin_and_dies_with_its_batch(self, detector):
        """The window starts at the covered batch's begin (an early
        announcement is not consumed by pre-begin work), and expires at
        that batch's end — a rank that compiles fast then dies is caught
        on the normal clock."""
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "grace", "rank": 0})
        time.sleep(1.0)  # announcement ages; must NOT consume the window
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        time.sleep(1.2)
        assert not detector.results.down_flag  # anchored at begin
        post_signal("127.0.0.1", 27757, {"kind": "end", "rank": 0})
        post_signal("127.0.0.1", 27757, {"kind": "begin", "rank": 0})
        time.sleep(1.5)  # > stall_timeout: grace is spent
        assert detector.results.down_flag

    def test_finished_rank_reuse_resets_state(self):
        """A new incarnation reusing a rank id whose previous life sent
        trainend must be monitored afresh (stale finished=True would
        skip it forever) with the compile allowance (fresh batches_done)."""
        d = DetectorServer(expected_ranks=2, port=27758, stall_timeout=0.5,
                           compile_grace=2.5).start()
        try:
            post_signal("127.0.0.1", 27758, {"kind": "begin", "rank": 0})
            post_signal("127.0.0.1", 27758, {"kind": "end", "rank": 0})
            post_signal("127.0.0.1", 27758, {"kind": "trainend", "rank": 0})
            # new incarnation: cold compile outlasts the stall timeout
            post_signal("127.0.0.1", 27758, {"kind": "begin", "rank": 0})
            time.sleep(1.2)
            assert not d.results.down_flag
            # ...but a rank that never finishes it still goes down
            deadline = time.time() + 10
            while not d.results.down_flag and time.time() < deadline:
                time.sleep(0.2)
            assert d.results.down_flag
        finally:
            d.stop()


class TestSilentRankDetection:
    """A rank that signals only grace/epoch and then dies has
    last_begin == last_end == 0, which the heartbeat-silence guard
    (last_seen > 0) never matches — 'seen but never began within the
    compile allowance' must be flagged as a stall."""

    @pytest.fixture
    def detector(self):
        d = DetectorServer(expected_ranks=2, port=27760, stall_timeout=0.5,
                           compile_grace=1.5).start()
        yield d
        d.stop()

    def _wait_down(self, d, deadline_s=10):
        deadline = time.time() + deadline_s
        while not d.results.down_flag and time.time() < deadline:
            time.sleep(0.1)
        return d.results.down_flag

    def test_grace_only_rank_death_detected(self, detector):
        post_signal("127.0.0.1", 27760, {"kind": "grace", "rank": 0})
        # ...and the rank dies before its first begin ever arrives
        assert self._wait_down(detector)

    def test_epoch_only_rank_death_detected(self, detector):
        post_signal("127.0.0.1", 27760, {"kind": "epoch", "rank": 0, "epoch": 2})
        assert self._wait_down(detector)
        # the restart point still honors the completed epochs it reported
        assert detector.results.epoch_num == 3

    def test_grace_only_rank_within_allowance_not_flagged(self, detector):
        post_signal("127.0.0.1", 27760, {"kind": "grace", "rank": 0})
        time.sleep(0.8)  # > stall_timeout, < compile_grace
        assert not detector.results.down_flag

    def test_begin_cancels_never_began_clock(self, detector):
        post_signal("127.0.0.1", 27760, {"kind": "grace", "rank": 0})
        post_signal("127.0.0.1", 27760, {"kind": "begin", "rank": 0})
        time.sleep(1.0)  # inside the (grace-covered) first-batch window
        assert not detector.results.down_flag


class TestFanoutParallel:
    """One unreachable host must not head-of-line-block every other
    host's restart notification: fan-out runs one thread per host."""

    def test_slow_host_does_not_delay_healthy_host(self):
        # staller: accepts on 127.0.0.3:<port> and never responds — each
        # sequential attempt would burn the full 3 s client timeout
        import socket

        port = 27761
        staller = socket.socket()
        staller.bind(("127.0.0.3", port))
        staller.listen(4)
        receiver = DetectorServer(expected_ranks=1, port=port,
                                  host="127.0.0.2").start()
        sender = DetectorServer(expected_ranks=1, port=port, host="127.0.0.1",
                                peer_hosts=["127.0.0.3", "127.0.0.2"]).start()
        try:
            t = threading.Thread(
                target=sender._fanout,
                args=({"kind": "otherdown", "epoch": 1},), daemon=True,
            )
            t0 = time.time()
            t.start()
            deadline = time.time() + 5
            while not receiver.results.down_flag and time.time() < deadline:
                time.sleep(0.05)
            elapsed = time.time() - t0
            assert receiver.results.down_flag, "healthy host never notified"
            # sequential delivery sits behind the staller's full retry
            # ladder (3 attempts x 3s timeouts + backoff ≈ 10s)
            assert elapsed < 5, f"fan-out serialized ({elapsed:.1f}s)"
        finally:
            sender.stop()
            receiver.stop()
            staller.close()


class TestWorkerOriginDownRelay:
    """A worker-side quorum-loss escalation (monitor_report_down) lands
    only on the main host's detector — it must be relayed to the peer
    hosts (one hop: relayed copies must not cascade back)."""

    def test_worker_otherdown_is_relayed_once(self):
        port = 27763
        receiver = DetectorServer(expected_ranks=1, port=port,
                                  host="127.0.0.2",
                                  peer_hosts=["127.0.0.1"]).start()
        sender = DetectorServer(expected_ranks=1, port=port, host="127.0.0.1",
                                peer_hosts=["127.0.0.2"]).start()
        try:
            # worker-originated: no relay flag
            post_signal("127.0.0.1", port, {"kind": "otherdown", "epoch": 2})
            deadline = time.time() + 5
            while not receiver.results.down_flag and time.time() < deadline:
                time.sleep(0.05)
            assert sender.results.down_flag
            # the peer host joined the restart round...
            assert receiver.results.down_flag
            assert receiver.results.epoch_num == 2
            # ...via a relay-flagged copy that did NOT cascade back and
            # re-resolve the sender's epoch (a cascade would loop the
            # two detectors against each other)
            time.sleep(0.5)
            assert sender.results.epoch_num == 2
        finally:
            sender.stop()
            receiver.stop()


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones(4)}
        save_checkpoint(str(tmp_path), 3, tree, meta={"epochs_done": 2})
        like = {"w": np.zeros((2, 3), np.float32), "b": np.zeros(4)}
        out, step, meta = restore_checkpoint(str(tmp_path), like)
        assert step == 3 and meta == {"epochs_done": 2}
        np.testing.assert_allclose(out["w"], tree["w"])

    def test_latest_and_prune(self, tmp_path):
        tree = {"x": np.zeros(2)}
        for s in range(5):
            save_checkpoint(str(tmp_path), s, tree)
        assert latest_step(str(tmp_path)) == 4
        prune_checkpoints(str(tmp_path), keep=2)
        assert latest_step(str(tmp_path)) == 4
        assert restore_checkpoint(str(tmp_path), tree, step=4) is not None
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(tmp_path), tree, step=0)

    def test_restore_empty_dir(self, tmp_path):
        assert restore_checkpoint(str(tmp_path), {"x": np.zeros(1)}) is None


@pytest.mark.slow
class TestAutoRecoveryCLI:
    @staticmethod
    def _env():
        env = dict(os.environ)
        # this exercises the host-side recovery machinery (detector,
        # restart, checkpoint restore) on a tiny SLP — force the CPU
        # backend so worker startup latency and chip contention can't
        # interact with the heartbeat timeout (round-1 flake)
        env["JAX_PLATFORMS"] = "cpu"
        return env

    def test_crash_recovery(self, tmp_path):
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli", "-auto-recover", "4s",
             "-np", "2", sys.executable, "examples/failure_recovery.py",
             "--n-epochs", "3", "--die-at-epoch", "1",
             "--ckpt-dir", str(tmp_path)],
            cwd=REPO, capture_output=True, text=True, timeout=350, env=self._env(),
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "restarted from epoch 1" in r.stdout
        assert "trained epochs [1, 3) OK" in r.stdout

    def test_hang_recovery(self, tmp_path):
        """Stall path: a worker sends begin-without-end and sleeps; the
        detector must flag it via the heartbeat timeout (not process exit)
        and the restart round must restore + finish."""
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli", "-auto-recover", "5s",
             "-np", "2", sys.executable, "examples/failure_recovery.py",
             "--n-epochs", "3", "--hang-at-epoch", "1",
             "--ckpt-dir", str(tmp_path)],
            # 5s period, not 3: a CPU-starved batch on a loaded 1-core box
            # can legitimately exceed 3s, and a begin-without-end past the
            # period reads as a hang — the detector then restarts BEFORE
            # the simulated stall, failing the 'simulating stall' assert
            cwd=REPO, capture_output=True, text=True, timeout=350, env=self._env(),
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "simulating stall" in r.stdout
        assert "restarted from epoch 1" in r.stdout
        assert "trained epochs [1, 3) OK" in r.stdout
