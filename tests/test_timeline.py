"""Flight recorder tests: ring bounding, spans, dumps, registry,
``kftrace`` merge + straggler analysis, and the /metrics rendering."""

import json
import os
import socket
import subprocess
import sys
import urllib.request

import pytest

from kungfu_tpu.monitor import timeline, traceview
from kungfu_tpu.monitor.registry import (
    REGISTRY,
    Histogram,
    MetricsRegistry,
)
from kungfu_tpu.utils import trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(trace.ENABLE_TRACE, raising=False)
    monkeypatch.delenv(timeline.DUMP_ENV, raising=False)
    monkeypatch.delenv(timeline.CAP_ENV, raising=False)
    timeline.reset()
    timeline.set_rank(None)
    trace.reset_trace_stats()
    yield
    timeline.reset()
    timeline.set_rank(None)
    trace.reset_trace_stats()


class TestRing:
    def test_bounding_and_drop_counting(self):
        timeline.reset(cap=8)
        for i in range(20):
            timeline.event("mark", f"m{i}", force=True)
        snap = timeline.snapshot()
        assert len(snap) == 8
        assert timeline.dropped() == 12
        # flight-recorder semantics: the NEWEST events survive
        assert [e["name"] for e in snap] == [f"m{i}" for i in range(12, 20)]

    def test_cap_from_env(self, monkeypatch):
        monkeypatch.setenv(timeline.CAP_ENV, "4")
        timeline.reset()
        for i in range(10):
            timeline.event("mark", f"m{i}", force=True)
        assert len(timeline.snapshot()) == 4
        assert timeline.dropped() == 6

    def test_drop_counter_published(self):
        before = REGISTRY.counter("kf_timeline_dropped_total").value
        timeline.reset(cap=2)
        for i in range(5):
            timeline.event("mark", f"m{i}", force=True)
        assert REGISTRY.counter("kf_timeline_dropped_total").value == before + 3

    def test_step_and_rank_stamping(self):
        timeline.set_rank(7)
        timeline.set_step(42)
        timeline.event("mark", "a", force=True)
        timeline.event("mark", "b", rank=3, force=True)
        a, b = timeline.snapshot()
        assert (a["rank"], a["step"]) == (7, 42)
        assert b["rank"] == 3  # explicit rank wins over the default


class TestSpan:
    def test_nesting_records_both(self):
        with timeline.span("collective", "outer", rank=0, force=True):
            with timeline.span("collective", "inner", rank=0, force=True):
                pass
        names = [e["name"] for e in timeline.snapshot()]
        # inner closes (and records) first
        assert names == ["inner", "outer"]
        for e in timeline.snapshot():
            assert e["dur"] > 0

    def test_exception_annotated_and_recorded(self):
        with pytest.raises(ValueError):
            with timeline.span("collective", "boom", force=True):
                raise ValueError("x")
        (ev,) = timeline.snapshot()
        assert ev["attrs"]["error"] == "ValueError"

    def test_feeds_trace_report(self):
        with timeline.span("collective", "spanned-op", force=True):
            pass
        rep = trace.trace_report()
        assert rep["spanned-op"]["count"] == 1
        assert "p95_ms" in rep["spanned-op"]

    def test_collective_span_feeds_latency_histogram(self):
        h = REGISTRY.histogram("kf_collective_latency_seconds",
                               plane="collective", op="probe_op")
        before = h.count
        with timeline.span("collective", "engine.probe", force=True,
                           op="probe_op", tag="t0"):
            pass
        assert h.count == before + 1


class TestDisabledPath:
    def test_span_is_shared_noop(self):
        s1 = timeline.span("collective", "a")
        s2 = timeline.span("device", "b")
        assert s1 is s2  # zero-allocation singleton
        with s1:
            pass
        assert timeline.snapshot() == []

    def test_event_records_nothing(self):
        timeline.event("mark", "quiet")
        timeline.event("send", "frame", nbytes=100)
        assert timeline.snapshot() == []
        assert timeline.dropped() == 0

    def test_env_enables(self, monkeypatch):
        monkeypatch.setenv(trace.ENABLE_TRACE, "1")
        timeline.event("mark", "loud")
        assert len(timeline.snapshot()) == 1

    def test_counted_kinds_tick_even_when_disabled(self):
        before = REGISTRY.counter("kf_engine_retries_total").value
        timeline.event("retry", "some-op", peer=1, attempt=0)
        assert REGISTRY.counter("kf_engine_retries_total").value == before + 1
        assert timeline.snapshot() == []  # counter ticked, ring untouched

    def test_chaos_counter_labeled_by_fault(self):
        before = REGISTRY.counter("kf_chaos_injections_total",
                                  what="delay").value
        timeline.event("chaos", "delay", ms=5)
        assert REGISTRY.counter(
            "kf_chaos_injections_total", what="delay").value == before + 1


class TestDump:
    def test_jsonl_round_trip(self, tmp_path):
        timeline.set_rank(3)
        with timeline.span("collective", "engine.all_reduce[16B]", rank=3,
                           force=True, op="all_reduce", tag="g", nbytes=16):
            pass
        timeline.event("chaos", "delay", rank=3, force=True, ms=7)
        path = str(tmp_path / "d.jsonl")
        n = timeline.dump(path)
        assert n == 2
        header, events = traceview.load_dump(path)
        assert header["rank"] == 3 and header["kftrace"] == 1
        assert [e["kind"] for e in events] == ["collective", "chaos"]
        assert events[0]["attrs"]["nbytes"] == 16

    def test_maybe_dump_env_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv(timeline.DUMP_ENV, str(tmp_path))
        timeline.set_rank(1)
        timeline.event("mark", "x", force=True)
        out = timeline.maybe_dump()
        assert out is not None and out.startswith(str(tmp_path))
        assert os.path.basename(out).startswith("trace-r1-")
        _, events = traceview.load_dump(out)
        assert len(events) == 1

    def test_maybe_dump_noop_without_env(self):
        timeline.event("mark", "x", force=True)
        assert timeline.maybe_dump() is None

    def test_maybe_dump_noop_when_empty(self, tmp_path, monkeypatch):
        monkeypatch.setenv(timeline.DUMP_ENV, str(tmp_path))
        assert timeline.maybe_dump() is None

    def test_self_check_rejects_corrupt_dump(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"kftrace": 1}\n{"kind": "mark"}\n')
        assert traceview.self_check([str(bad)]) == 1
        good = tmp_path / "good.jsonl"
        timeline.event("mark", "ok", force=True)
        timeline.dump(str(good))
        assert traceview.self_check([str(good)]) == 0

    def test_unknown_kind_rejected(self, tmp_path):
        bad = tmp_path / "k.jsonl"
        bad.write_text(json.dumps({
            "ts": 0.0, "rank": 0, "step": -1, "kind": "bogus",
            "name": "x", "dur": 0.0, "attrs": {},
        }) + "\n")
        with pytest.raises(traceview.DumpError):
            traceview.load_dump(str(bad))


class TestRegistry:
    def test_counter_gauge_render(self):
        reg = MetricsRegistry()
        reg.counter("c_total", kind="x").inc(3)
        reg.gauge("g").set(1.5)
        text = reg.render_prometheus()
        assert 'c_total{kind="x"} 3' in text
        assert "g 1.5" in text

    def test_histogram_percentiles(self):
        h = Histogram()
        for ms in range(1, 101):  # 1..100 ms
            h.observe(ms / 1000.0)
        s = h.summary()
        assert s["count"] == 100
        assert s["min"] == pytest.approx(0.001)
        assert s["max"] == pytest.approx(0.1)
        assert 0.03 <= s["p50"] <= 0.08  # true median 50.5 ms, bucketed
        assert 0.08 <= s["p95"] <= 0.11
        assert s["p99"] <= s["max"] + 1e-9

    def test_histogram_render_lines(self):
        reg = MetricsRegistry()
        reg.histogram("lat_seconds", op="ar").observe(0.003)
        text = reg.render_prometheus()
        assert 'lat_seconds_bucket{le="+Inf",op="ar"} 1' in text
        assert 'lat_seconds_count{op="ar"} 1' in text
        assert "lat_seconds_sum" in text

    def test_help_type_headers_once_per_family(self):
        """Stock-scraper metadata: # HELP/# TYPE per metric family (one
        header even across label variants), sample lines untouched."""
        reg = MetricsRegistry()
        reg.counter("kf_engine_retries_total").inc(2)
        reg.gauge("g").set(1.5)
        reg.histogram("lat_seconds", op="a").observe(0.003)
        reg.histogram("lat_seconds", op="b").observe(0.004)
        text = reg.render_prometheus()
        # known metric gets its curated help line; unknown the fallback
        assert ("# HELP kf_engine_retries_total engine send retries "
                "after transient wire faults") in text
        assert "# TYPE kf_engine_retries_total counter" in text
        assert "# HELP g kungfu-tpu metric" in text
        assert "# TYPE g gauge" in text
        assert text.count("# TYPE lat_seconds histogram") == 1
        # metadata precedes the family's first sample
        lines = text.splitlines()
        assert lines.index("# TYPE kf_engine_retries_total counter") \
            < lines.index("kf_engine_retries_total 2")
        # sample encoding byte-compatible with the pre-HELP rendering
        assert "kf_engine_retries_total 2" in lines
        assert "g 1.5" in lines
        assert 'lat_seconds_bucket{le="+Inf",op="a"} 1' in text

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("m")
        with pytest.raises(TypeError):
            reg.gauge("m")

    def test_trace_report_gains_tails(self):
        with trace.trace_scope("tailed", force=True):
            pass
        rep = trace.trace_report()["tailed"]
        # byte-compatible original keys
        assert set(rep) >= {"count", "total_s", "mean_ms"}
        assert rep["min_ms"] <= rep["p50_ms"] <= rep["max_ms"] + 1e-9
        assert rep["p95_ms"] >= rep["p50_ms"] - 1e-9


def _span_ev(ts, rank, step, op, tag, dur):
    return {"ts": ts, "rank": rank, "step": step, "kind": "collective",
            "name": f"engine.{op}", "dur": dur,
            "attrs": {"op": op, "tag": tag}}


def _write_dump(path, rank, events):
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"kftrace": 1, "rank": rank, "pid": 100 + rank,
                            "dropped": 0, "wall": 0.0}) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")


@pytest.fixture
def planted_dumps(tmp_path):
    """3 synthetic rank dumps: rank 2 is 10x slower on every collective
    and carries a chaos delay inside its slow windows."""
    paths = []
    for rank in range(3):
        events = []
        for step in range(3):
            t = 100.0 + step
            dur = 0.10 if rank == 2 else 0.01
            events.append(_span_ev(t, rank, step, "all_reduce",
                                   f"grad{step}", dur))
            if rank == 2:
                events.append({"ts": t + 0.02, "rank": 2, "step": step,
                               "kind": "chaos", "name": "delay",
                               "dur": 0.0, "attrs": {"ms": 80}})
        p = str(tmp_path / f"trace-r{rank}.jsonl")
        _write_dump(p, rank, events)
        paths.append(p)
    return paths


class TestKftrace:
    def test_straggler_report_names_planted_rank(self, planted_dumps):
        events = traceview.load_all(planted_dumps)
        assert traceview.straggler_verdict(events) == 2
        report = traceview.render_report(events)
        assert "straggler verdict: rank 2" in report
        assert "step 0: rank 2" in report
        # the injected delay overlaps the spike and is attributed
        assert "chaos:delay@rank2" in report

    def test_skew_rows(self, planted_dumps):
        events = traceview.load_all(planted_dumps)
        rows = traceview.skew_rows(events)
        assert len(rows) == 3  # one group per step's grad tag
        for r in rows:
            assert r["slowest_rank"] == 2
            assert r["skew_s"] == pytest.approx(0.09, rel=0.01)

    def test_chrome_trace_merge(self, planted_dumps):
        events = traceview.load_all(planted_dumps)
        trace_obj = traceview.chrome_trace(events)
        te = trace_obj["traceEvents"]
        assert {e["pid"] for e in te} == {0, 1, 2}
        assert any(e.get("ph") == "X" for e in te)  # spans
        assert any(e.get("ph") == "i" for e in te)  # chaos instants
        # rebased timestamps: earliest event at ts 0
        assert min(e["ts"] for e in te if e["ph"] != "M") == 0.0

    def test_merge_cli(self, planted_dumps, tmp_path, capsys):
        out = str(tmp_path / "trace.json")
        rc = traceview.main(["merge", "-o", out] + planted_dumps)
        assert rc == 0
        with open(out) as f:
            obj = json.load(f)
        assert "traceEvents" in obj and len(obj["traceEvents"]) > 9

    def test_report_cli(self, planted_dumps, capsys):
        rc = traceview.main(["report"] + planted_dumps)
        assert rc == 0
        assert "straggler verdict: rank 2" in capsys.readouterr().out

    def test_script_self_check(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kftrace"),
             "--self-check"],
            capture_output=True, timeout=60,
        )
        assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


class TestMetricsServer:
    def _scrape(self, port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            return r.read().decode()

    def test_ephemeral_port_and_histogram_lines(self):
        from kungfu_tpu.monitor.metrics import MetricsServer, NetMonitor

        REGISTRY.histogram("kf_collective_latency_seconds",
                           plane="collective", op="scrape_probe").observe(0.02)
        m = NetMonitor(period=0.1)
        s = MetricsServer(m, port=0).start()
        try:
            assert s.port != 0  # the ACTUAL bound port is exposed
            text = self._scrape(s.port)
            assert "kf_collective_latency_seconds_bucket" in text
            assert 'op="scrape_probe"' in text
            assert "kf_collective_latency_seconds_count" in text
        finally:
            s.stop()

    def test_broken_extra_fn_does_not_500_the_scrape(self):
        """A raised exception inside extra_fn must not take the whole
        endpoint down: healthy sections render, the failure appears as a
        comment line (legal exposition-format noise)."""
        from kungfu_tpu.monitor.metrics import MetricsServer, NetMonitor

        REGISTRY.counter("kf_scrape_probe_total").inc()
        m = NetMonitor(period=0.1)
        m.egress("peer:1", 512)

        def broken_extra():
            raise RuntimeError("gns collector exploded")

        s = MetricsServer(m, port=0, extra_fn=broken_extra).start()
        try:
            text = self._scrape(s.port)  # 200, not 500
            assert 'kf_egress_bytes_total{peer="peer:1"} 512' in text
            assert "kf_scrape_probe_total 1" in text
            assert "# error: extra_fn: RuntimeError: gns collector exploded" in text
        finally:
            s.stop()

    def test_registry_render_error_isolated(self, monkeypatch):
        from kungfu_tpu.monitor import metrics as metrics_mod
        from kungfu_tpu.monitor.metrics import MetricsServer, NetMonitor

        m = NetMonitor(period=0.1)
        m.ingress("peer:2", 64)
        monkeypatch.setattr(
            metrics_mod.REGISTRY, "render_prometheus",
            lambda: (_ for _ in ()).throw(ValueError("bad metric")))
        s = MetricsServer(m, port=0).start()
        try:
            text = self._scrape(s.port)
            assert 'kf_ingress_bytes_total{peer="peer:2"} 64' in text
            assert "# error: registry: ValueError: bad metric" in text
        finally:
            s.stop()

    def test_taken_port_degrades_to_ephemeral(self):
        from kungfu_tpu.monitor.metrics import MetricsServer, NetMonitor

        squatter = socket.socket()
        squatter.bind(("0.0.0.0", 0))
        squatter.listen(1)
        taken = squatter.getsockname()[1]
        try:
            m = NetMonitor(period=0.1)
            s = MetricsServer(m, port=taken).start()  # must NOT raise
            try:
                assert s.port != taken
                assert "kf" in self._scrape(s.port) or self._scrape(s.port) == "\n"
            finally:
                s.stop()
        finally:
            squatter.close()


class TestEngineIntegration:
    def test_collective_spans_and_frame_marks(self, monkeypatch):
        """A 2-peer allreduce under tracing leaves rank-attributed
        collective spans plus send/recv frame marks in the ring."""
        import threading

        import numpy as np

        monkeypatch.setenv(trace.ENABLE_TRACE, "1")
        from kungfu_tpu.comm.engine import CollectiveEngine
        from kungfu_tpu.comm.host import HostChannel
        from kungfu_tpu.plan import PeerID, PeerList
        from kungfu_tpu.plan.strategy import Strategy

        peers = PeerList.of(*(PeerID("127.0.0.1", 23150 + i) for i in range(2)))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        engines = [
            CollectiveEngine(c, peers, strategy=Strategy.STAR) for c in chans
        ]
        outs = [None, None]

        def run(i):
            outs[i] = engines[i].all_reduce(np.ones(4, np.float32))

        ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        for c in chans:
            c.close()
        np.testing.assert_allclose(outs[0], 2 * np.ones(4))
        snap = timeline.snapshot()
        colls = [e for e in snap if e["kind"] == "collective"]
        assert {e["rank"] for e in colls} == {0, 1}
        assert all(e["attrs"]["op"] == "all_reduce" for e in colls)
        assert all(e["dur"] > 0 for e in colls)
        # both peers share one rendezvous tag — kftrace's skew unit
        assert len({e["attrs"]["tag"] for e in colls}) == 1
