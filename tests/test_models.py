"""Model zoo smoke + correctness tests (small shapes, CPU mesh)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO_BENCH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmarks")

from kungfu_tpu.models import (
    MLP,
    ResNet,
    Transformer,
    TransformerConfig,
    VGG,
    fake_grads,
    fake_model_sizes,
    mnist_slp,
    nn,
)


class TestMLP:
    def test_slp_shapes_and_grad(self):
        m = mnist_slp()
        params = m.init(jax.random.PRNGKey(0))
        assert nn.num_params(params) == 7850
        x = np.random.RandomState(0).rand(4, 28, 28).astype(np.float32)
        y = np.array([1, 2, 3, 4])
        logits = m.apply(params, x)
        assert logits.shape == (4, 10)
        loss, grads = jax.value_and_grad(m.loss)(params, (x, y))
        assert np.isfinite(float(loss))
        assert grads["dense_0"]["w"].shape == (784, 10)

    def test_training_reduces_loss(self):
        m = MLP([32])
        params = m.init(jax.random.PRNGKey(1))
        rng = np.random.RandomState(1)
        x = rng.rand(64, 784).astype(np.float32)
        y = (x.sum(1) > x.sum(1).mean()).astype(np.int32)

        @jax.jit
        def step(p):
            l, g = jax.value_and_grad(m.loss)(p, (x, y))
            return l, jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)

        l0, params = step(params)
        for _ in range(20):
            l, params = step(params)
        assert float(l) < float(l0)


class TestResNet:
    @pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
    def test_tiny_forward_backward(self):
        m = ResNet(50, num_classes=10, width=8)
        params, state = m.init(jax.random.PRNGKey(0))
        x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
        y = np.array([1, 2])
        (loss, new_state), grads = jax.value_and_grad(m.loss, has_aux=True)(
            params, state, (x, y), train=True, dtype=jnp.float32
        )
        assert np.isfinite(float(loss))
        # BN running stats updated
        assert not np.allclose(
            np.asarray(new_state["stem_bn"]["mean"]), np.asarray(state["stem_bn"]["mean"])
        )
        # eval path
        logits, _ = m.apply(params, state, x, train=False, dtype=jnp.float32)
        assert logits.shape == (2, 10)

    def test_s2d_stem_matches_direct_conv(self):
        """The space-to-depth stem is the SAME linear map as the 7x7/s2
        conv (MXU lane packing, not an architecture change): outputs and
        the gradient w.r.t. the original 7x7 parameter must match the
        direct conv to float tolerance, and odd sizes fall back."""
        key = jax.random.PRNGKey(0)
        p = nn.conv_init(key, 3, 16, (7, 7))
        x = jnp.asarray(
            np.random.RandomState(1).randn(2, 64, 64, 3), jnp.float32
        )
        a = nn.conv_apply(p, x, stride=2)
        b = nn.conv_stem_s2d_apply(p, x)
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
        ga = jax.grad(lambda w: jnp.sum(
            nn.conv_apply({"w": w}, x, stride=2) ** 2))(p["w"])
        gb = jax.grad(lambda w: jnp.sum(
            nn.conv_stem_s2d_apply({"w": w}, x) ** 2))(p["w"])
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=1e-4, atol=1e-4)
        # odd spatial size: falls back to the direct conv path
        x_odd = x[:, :63, :63, :]
        np.testing.assert_allclose(
            np.asarray(nn.conv_stem_s2d_apply(p, x_odd)),
            np.asarray(nn.conv_apply(p, x_odd, stride=2)),
            rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
    def test_real_resnet50_param_count(self):
        m = ResNet(50, num_classes=1000)
        params, _ = m.init(jax.random.PRNGKey(0))
        n = nn.num_params(params)
        assert 25.4e6 < n < 25.8e6, n  # ~25.56M

    def test_deep_variants(self):
        """101/152 stage tables build and run (tiny width)."""
        for depth, blocks in ((101, 33), (152, 50)):
            m = ResNet(depth, num_classes=10, width=8)
            params, state = m.init(jax.random.PRNGKey(0))
            n_blocks = sum(
                1 for k in params if k[0] == "s" and k[1].isdigit()
            )
            assert n_blocks == blocks
            x = np.random.RandomState(0).rand(1, 32, 32, 3).astype(np.float32)
            logits, _ = m.apply(params, state, x, train=False, dtype=jnp.float32)
            assert logits.shape == (1, 10)


class TestVGG:
    @pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
    def test_tiny_forward_backward(self):
        m = VGG(11, num_classes=10, hidden=64)
        params, state = m.init(jax.random.PRNGKey(0))
        x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
        y = np.array([1, 2])
        (loss, new_state), grads = jax.value_and_grad(m.loss, has_aux=True)(
            params, state, (x, y), train=True, dtype=jnp.float32
        )
        assert np.isfinite(float(loss))
        assert not np.allclose(
            np.asarray(new_state["conv0_bn"]["mean"]),
            np.asarray(state["conv0_bn"]["mean"]),
        )
        logits, _ = m.apply(params, state, x, train=False, dtype=jnp.float32)
        assert logits.shape == (2, 10)

    def test_no_bn_variant(self):
        m = VGG(11, num_classes=10, batch_norm=False, hidden=64)
        params, state = m.init(jax.random.PRNGKey(0))
        assert state == {}
        x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
        logits, ns = m.apply(params, state, x, dtype=jnp.float32)
        assert logits.shape == (2, 10) and ns == {}

    def test_vgg16_param_count(self):
        m = VGG(16, num_classes=1000)
        params, _ = m.init(jax.random.PRNGKey(0))
        n = nn.num_params(params)
        # 14.71M conv + 2.10M fc1 + 4.10M head + BN affine (~8.5k x2)
        assert 20.5e6 < n < 21.5e6, n


class TestTransformer:
    @pytest.mark.parametrize("pos,causal", [("rope", True), ("learned", False)])
    def test_forward_backward(self, pos, causal):
        cfg = TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq=16, causal=causal, pos=pos, dtype="float32",
        )
        m = Transformer(cfg)
        params = m.init(jax.random.PRNGKey(0))
        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        tgt = np.roll(ids, -1, axis=1)
        loss, grads = jax.value_and_grad(m.loss)(params, (ids, tgt))
        assert np.isfinite(float(loss))
        g = grads["layer_0"]["wq"]["w"]
        assert np.abs(np.asarray(g)).sum() > 0

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq=8, causal=True, pos="rope", dtype="float32",
        )
        m = Transformer(cfg)
        params = m.init(jax.random.PRNGKey(0))
        ids = np.arange(8)[None, :] % 64
        logits1 = np.asarray(m.apply(params, ids))
        ids2 = ids.copy()
        ids2[0, -1] = (ids[0, -1] + 9) % 64
        logits2 = np.asarray(m.apply(params, ids2))
        np.testing.assert_allclose(logits1[0, :-1], logits2[0, :-1], atol=1e-5)
        assert not np.allclose(logits1[0, -1], logits2[0, -1])


class TestBNVariants:
    """benchmarks/bn_sweep.py variant candidates: bf16_norm must be a
    pure precision change (identical f32 stats, bf16-rounded output);
    ghost BN must keep shapes and fall back cleanly."""

    def _xpb(self, batch=32, ch=8):
        from kungfu_tpu.models import nn

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((batch, 4, 4, ch)), jnp.bfloat16)
        p = nn.batchnorm_init(ch)
        p["scale"] = jnp.asarray(rng.uniform(0.5, 1.5, ch), jnp.float32)
        p["bias"] = jnp.asarray(rng.standard_normal(ch), jnp.float32)
        st = nn.batchnorm_state_init(ch)
        return x, p, st

    def test_bf16_norm_matches_prod(self):
        import sys
        sys.path.insert(0, REPO_BENCH)
        from bn_sweep import bn_variant
        from kungfu_tpu.models import nn

        x, p, st = self._xpb()
        y0, s0 = nn.batchnorm_apply(p, st, x, train=True)
        y1, s1 = bn_variant("bf16_norm")(p, st, x, train=True)
        # stats path is bit-identical f32
        for k in s0:
            np.testing.assert_array_equal(np.asarray(s0[k]), np.asarray(s1[k]))
        # output differs only by bf16 rounding of the elementwise chain
        np.testing.assert_allclose(
            np.asarray(y0, np.float32), np.asarray(y1, np.float32),
            atol=0.05, rtol=0.05)

    def test_bn_compute_dtype_default_and_optout(self, monkeypatch):
        """Round-5 BN-tax fix: the elementwise chain defaults to the
        activation dtype; stats stay bit-identical f32; KF_TPU_BN_COMPUTE
        =f32 (or an explicit compute_dtype) restores the legacy chain."""
        from kungfu_tpu.models import nn

        x, p, st = self._xpb()
        monkeypatch.delenv("KF_TPU_BN_COMPUTE", raising=False)
        y_act, s_act = nn.batchnorm_apply(p, st, x, train=True)
        y_f32, s_f32 = nn.batchnorm_apply(p, st, x, train=True,
                                          compute_dtype=jnp.float32)
        for k in s_act:
            np.testing.assert_array_equal(np.asarray(s_act[k]),
                                          np.asarray(s_f32[k]))
        assert y_act.dtype == x.dtype == y_f32.dtype
        np.testing.assert_allclose(
            np.asarray(y_act, np.float32), np.asarray(y_f32, np.float32),
            atol=0.05, rtol=0.05)
        # env opt-out is exactly the explicit-f32 chain
        monkeypatch.setenv("KF_TPU_BN_COMPUTE", "f32")
        y_env, s_env = nn.batchnorm_apply(p, st, x, train=True)
        np.testing.assert_array_equal(np.asarray(y_env), np.asarray(y_f32))
        # f32 activations: both chains are the same f32 math
        xf = x.astype(jnp.float32)
        monkeypatch.delenv("KF_TPU_BN_COMPUTE", raising=False)
        ya, _ = nn.batchnorm_apply(p, st, xf, train=True)
        yb, _ = nn.batchnorm_apply(p, st, xf, train=True,
                                   compute_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))

    def test_ghost_groups_and_fallback(self):
        import sys
        sys.path.insert(0, REPO_BENCH)
        from bn_sweep import bn_variant
        from kungfu_tpu.models import nn

        x, p, st = self._xpb(batch=32)
        y, s = bn_variant("ghost16")(p, st, x, train=True)
        assert y.shape == x.shape and y.dtype == x.dtype
        assert np.isfinite(np.asarray(s["mean"])).all()
        # per-group normalization: each 16-sample group ~zero mean
        yg = np.asarray(y, np.float32).reshape(2, -1, x.shape[-1])
        centered = (yg - np.asarray(p["bias"])) / np.asarray(p["scale"])
        assert abs(centered.mean(axis=1)).max() < 0.05
        # batch == group size falls back to prod exactly
        xs, ps, sts = self._xpb(batch=16)
        y0, _ = nn.batchnorm_apply(ps, sts, xs, train=True)
        y1, _ = bn_variant("ghost16")(ps, sts, xs, train=True)
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))


class TestFakeModels:
    def test_totals(self):
        from kungfu_tpu.models.fake import total_params

        assert total_params("slp-mnist") == 7850
        assert 25e6 < total_params("resnet50-imagenet") < 26e6
        assert 130e6 < total_params("vgg16-imagenet") < 140e6
        assert 100e6 < total_params("bert") < 120e6

    def test_grads(self):
        gs = fake_grads("slp-mnist", stacked=4)
        assert gs[0].shape == (4, 7840)
        with pytest.raises(ValueError):
            fake_model_sizes("nope")
