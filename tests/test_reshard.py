"""Elastic ZeRO re-sharding from the step boundary
(`kungfu_tpu.elastic.reshard`): leaderless re-carve across membership
changes, ring-buddy redundancy for dead ranks, and the bitwise
elastic-vs-fixed-world guarantee — including the GPT config whose
replicated optimizer state cannot fit a single rank's budget.
"""

import math
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.elastic.reshard import ZeroBoundary
from kungfu_tpu.parallel.zero import zero_train_step

from tests._util import run_all


def _params(sizes=((13, 7), (7,), (7, 5))):
    rng = np.random.RandomState(0)
    return {
        f"w{i}": jnp.asarray(rng.randn(*s), jnp.float32)
        for i, s in enumerate(sizes)
    }


def _loss_fn(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w0"] + params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def _batch(n=16):
    rng = np.random.RandomState(1)
    return (jnp.asarray(rng.randn(n, 13), jnp.float32),
            jnp.asarray(rng.randn(n, 5), jnp.float32))


def _total(params):
    return int(sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params)))


def _hand_repad(opt, total, new_n):
    """The independent reference: host-repad every full flat vector to
    the new chunk geometry by plain numpy."""
    new_padded = math.ceil(total / new_n) * new_n

    def leaf(a):
        a = np.asarray(a)
        if a.ndim == 0:
            return a
        buf = np.zeros((new_padded,), a.dtype)
        buf[:total] = a[:total]
        return buf

    return jax.tree_util.tree_map(leaf, opt)


class TestZeroBoundaryFullMode:
    """Single-controller worlds: every vector is locally addressable,
    recarve is pure host slicing."""

    def _train(self, comm, steps=2, stage=2):
        params, batch = _params(), _batch()
        z = zero_train_step(_loss_fn, optax.adam(1e-2), comm, stage=stage)
        o = z.init_opt(params)
        p = z.init_params(params)
        for _ in range(steps):
            p, o, _ = z.step(p, o, batch)
        return z, p, o, params, batch

    def test_commit_recarve_place_matches_hand_repad(self):
        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c2 = Communicator(devices=devs[:2], local_size=2, version=1)
        z4, p, o, params, _ = self._train(c4)
        total = _total(params)

        b = ZeroBoundary()
        b.commit(2, o, params)
        assert b.step() == 2 and b.old_n == 4
        b.recarve(2)
        got = b.place(c2)
        want = _hand_repad(o, total, 2)
        for a, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(w))

    def test_live_4to2_shrink_bitwise_vs_fixed_world(self):
        """The headline elastic guarantee: training through a live 4->2
        re-carve continues BITWISE identically to a non-elastic 2-rank
        run restored from the same committed boundary."""
        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c2 = Communicator(devices=devs[:2], local_size=2, version=1)
        z4, p, o, params, batch = self._train(c4)
        total = _total(params)

        # elastic path: boundary -> recarve -> place -> keep training
        b = ZeroBoundary()
        b.commit(2, o, params)
        b.recarve(2)
        o_el = b.place(c2)
        z2 = zero_train_step(_loss_fn, optax.adam(1e-2), c2, stage=2)
        p_el = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a),
                                     c2.replicated_sharding()), p)
        p_el, o_el, _ = z2.step(p_el, o_el, batch)

        # fixed-world path: the same committed state, hand-repadded and
        # placed as if the job had been restarted at n=2
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharded = NamedSharding(c2.mesh, P(c2.axis))
        o_fx = jax.tree_util.tree_map(
            lambda a: (jax.device_put(a, sharded) if a.ndim
                       else jax.device_put(jnp.asarray(a),
                                           c2.replicated_sharding())),
            _hand_repad(o, total, 2))
        z2fx = zero_train_step(_loss_fn, optax.adam(1e-2), c2, stage=2)
        p_fx = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a),
                                     c2.replicated_sharding()), p)
        p_fx, o_fx, _ = z2fx.step(p_fx, o_fx, batch)

        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p_el[k]), np.asarray(p_fx[k]), err_msg=k)
        for a, w in zip(jax.tree_util.tree_leaves(o_el),
                        jax.tree_util.tree_leaves(o_fx)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(w))

    def test_recarve_before_commit_raises(self):
        with pytest.raises(ValueError, match="commit"):
            ZeroBoundary().recarve(2)

    def test_place_wrong_world_raises(self):
        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c2 = Communicator(devices=devs[:2], local_size=2, version=1)
        _, p, o, params, _ = self._train(c4, steps=1)
        b = ZeroBoundary()
        b.commit(1, o, params)
        with pytest.raises(ValueError, match="recarve"):
            b.place(c2)

    def test_grow_2_to_8(self):
        devs = jax.devices()
        c2 = Communicator(devices=devs[:2], local_size=2, version=0)
        c8 = Communicator(devices=devs[:8], local_size=8, version=1)
        _, p, o, params, _ = self._train(c2, steps=1)
        total = _total(params)
        b = ZeroBoundary()
        b.commit(1, o, params)
        b.recarve(8)
        got = b.place(c8)
        want = _hand_repad(o, total, 8)
        for a, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(w))

    def test_stage3_param_shard_recarves_too(self):
        """ZeRO-3's parameter shard is one more flat vector: the same
        boundary machinery re-carves it (commit it as its own tree)."""
        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c2 = Communicator(devices=devs[:2], local_size=2, version=1)
        z4, p_shard, o, params, batch = self._train(c4, steps=1, stage=3)
        total = _total(params)
        b = ZeroBoundary()
        b.commit(1, {"p": p_shard}, params)
        b.recarve(2)
        got = b.place(c2)["p"]
        want = _hand_repad({"p": p_shard}, total, 2)["p"]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the re-carved shard trains on the new world: gather matches
        # the old world's gather on [0, total)
        z2 = zero_train_step(_loss_fn, optax.adam(1e-2), c2, stage=3)
        z2.init_opt(params)
        z2.init_params(params)  # binds the stage-3 geometry
        full_new = z2.gather_params(got)
        full_old = z4.gather_params(p_shard)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(full_new[k]), np.asarray(full_old[k]), err_msg=k)


class TestGPTMemoryBudget:
    """The acceptance gate: a GPT config whose replicated optimizer
    state exceeds a single rank's budget trains under ZeRO-2 through a
    live 4->2 shrink with a bitwise-checked state re-carve."""

    BUDGET_BYTES = 768 << 10  # the per-rank optimizer-state budget

    def _gpt(self):
        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)

        cfg = TransformerConfig(vocab_size=512, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq=16,
                                dropout=0.0, dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ids = np.random.RandomState(2).randint(0, 512, size=(8, 16))
        batch = (jnp.asarray(ids, jnp.int32), jnp.asarray(ids, jnp.int32))

        def loss_fn(p, b):
            return model.loss(p, b, train=False)

        return params, batch, loss_fn

    def test_gpt_trains_sharded_through_live_shrink(self):
        from kungfu_tpu.parallel.zero import (opt_state_bytes,
                                              opt_state_bytes_per_device)

        params, batch, loss_fn = self._gpt()
        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c2 = Communicator(devices=devs[:2], local_size=2, version=1)

        # the replicated optimizer state does NOT fit the budget
        replicated = optax.adam(1e-3).init(params)
        assert opt_state_bytes(replicated) > self.BUDGET_BYTES, \
            "config too small to witness the memory claim"

        z4 = zero_train_step(loss_fn, optax.adam(1e-3), c4, stage=2)
        o = z4.init_opt(params)
        # ...but the ZeRO shard on each of the 4 ranks does
        assert opt_state_bytes_per_device(o) < self.BUDGET_BYTES
        p = params
        for _ in range(2):
            p, o, _ = z4.step(p, o, batch)

        total = _total(params)
        b = ZeroBoundary()
        b.commit(2, o, params)
        b.recarve(2)
        got = b.place(c2)
        want = _hand_repad(o, total, 2)
        for a, w in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(w))
        # and training continues on the shrunk world
        z2 = zero_train_step(loss_fn, optax.adam(1e-3), c2, stage=2)
        p2 = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a),
                                     c2.replicated_sharding()), p)
        p2, got, loss = z2.step(p2, got, batch)
        assert np.isfinite(float(loss))


# ==========================================================================
# chunk mode: one process per rank, segments over real host channels
# ==========================================================================

BASE_PORT = 28400
_port_gen = [BASE_PORT]


def _mk_world(n):
    from kungfu_tpu.comm.host import HostChannel
    from kungfu_tpu.plan import PeerID, PeerList

    _port_gen[0] += n + 2
    base = _port_gen[0]
    peers = PeerList.of(*(PeerID("127.0.0.1", base + i) for i in range(n)))
    chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]

    class _FakePeer:
        def __init__(self, chan, self_id):
            self.channel = chan
            self.config = type("C", (), {"self_id": self_id})()

    fakes = [_FakePeer(c, p) for c, p in zip(chans, peers)]
    return peers, chans, fakes


def _chunks_of(full, total, n):
    chunk = math.ceil(total / n)
    buf = np.zeros((chunk * n,), full.dtype)
    buf[:total] = full[:total]
    return [buf[r * chunk:(r + 1) * chunk] for r in range(n)]


class TestZeroBoundaryChunkMode:
    TOTAL = 10

    def _vectors(self):
        rng = np.random.RandomState(9)
        return {
            "mu": rng.randn(self.TOTAL).astype(np.float32),
            "nu": rng.randn(self.TOTAL).astype(np.float32),
        }

    def _boundaries(self, vecs, n, step=5):
        """One committed ZeroBoundary per rank, chunk mode."""
        out = []
        mu = _chunks_of(vecs["mu"], self.TOTAL, n)
        nu = _chunks_of(vecs["nu"], self.TOTAL, n)
        for r in range(n):
            b = ZeroBoundary()
            b.commit_local(
                step, {"mu": mu[r], "nu": nu[r], "count": np.int64(step)},
                total=self.TOTAL, old_n=n, my_old=r)
            out.append(b)
        return out

    def test_recarve_4_to_2(self):
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)
        bs = self._boundaries(vecs, 4)
        try:
            new_workers = type(peers).of(peers[0], peers[1])
            run_all([
                lambda b=b, f=f: b.recarve(
                    2, peer=f, old_workers=peers, new_workers=new_workers,
                    tag="t42")
                for b, f in zip(bs, fakes)
            ], timeout=60)
        finally:
            for c in chans:
                c.close()
        want_mu = _chunks_of(vecs["mu"], self.TOTAL, 2)
        want_nu = _chunks_of(vecs["nu"], self.TOTAL, 2)
        for r in range(2):
            step, vec, scal = bs[r].chunks()
            assert step == 5
            # dict keys flatten sorted: leaf 0 = count (scalar),
            # leaves 1/2 = mu/nu
            np.testing.assert_array_equal(vec[1], want_mu[r])
            np.testing.assert_array_equal(vec[2], want_nu[r])
        # leavers dropped their stale shard
        for r in (2, 3):
            _, vec, _ = bs[r].chunks()
            assert vec == {}

    def test_recarve_2_to_4_with_joiners(self):
        """Growth with pure joiners: new ranks receive everything,
        including the replicated scalars and the boundary step."""
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)  # 2 old + 2 joiners
        old_workers = type(peers).of(peers[0], peers[1])
        bs = self._boundaries(vecs, 2, step=7)  # boundaries for old ranks
        joiners = []
        for _ in range(2):
            b = ZeroBoundary()
            # structure template: one fresh chunk-sized tree
            b.join({"mu": np.zeros(3, np.float32),
                    "nu": np.zeros(3, np.float32),
                    "count": np.int64(0)},
                   {"w": np.zeros(self.TOTAL, np.float32)}, old_n=2)
            joiners.append(b)
        all_bs = bs + joiners
        try:
            run_all([
                lambda b=b, f=f: b.recarve(
                    4, peer=f, old_workers=old_workers, new_workers=peers,
                    tag="t24")
                for b, f in zip(all_bs, fakes)
            ], timeout=60)
        finally:
            for c in chans:
                c.close()
        want_mu = _chunks_of(vecs["mu"], self.TOTAL, 4)
        want_nu = _chunks_of(vecs["nu"], self.TOTAL, 4)
        for r in range(4):
            step, vec, scal = all_bs[r].chunks()
            assert step == 7, f"rank {r} did not adopt the boundary step"
            np.testing.assert_array_equal(vec[1], want_mu[r])
            np.testing.assert_array_equal(vec[2], want_nu[r])
        # joiners adopted the replicated scalar from the serving rank
        _, _, scal = all_bs[2].chunks()
        assert int(list(scal.values())[0]) == 7

    def test_dead_ranks_served_from_ring_buddies(self):
        """The unplanned 4->2 shrink: ranks 1 and 3 DIE after the
        boundary commit.  Their chunks survive on their ring
        predecessors (ranks 0 and 2) via replicate_ring, so the
        survivors still assemble the full re-carved state —
        leaderlessly, no global snapshot anywhere."""
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)
        bs = self._boundaries(vecs, 4)
        try:
            # buddy replication at the committed boundary (all 4 alive)
            run_all([
                lambda b=b, f=f: b.replicate_ring(f.channel, peers, tag="rb")
                for b, f in zip(bs, fakes)
            ], timeout=60)
            # ranks 1 and 3 die; survivors re-carve to [w0, w2]
            new_workers = type(peers).of(peers[0], peers[2])
            run_all([
                lambda b=b, f=f: b.recarve(
                    2, peer=f, old_workers=peers, new_workers=new_workers,
                    tag="tdead", dead=(1, 3))
                for b, f in ((bs[0], fakes[0]), (bs[2], fakes[2]))
            ], timeout=60)
        finally:
            for c in chans:
                c.close()
        want_mu = _chunks_of(vecs["mu"], self.TOTAL, 2)
        want_nu = _chunks_of(vecs["nu"], self.TOTAL, 2)
        for new_r, b in ((0, bs[0]), (1, bs[2])):
            _, vec, _ = b.chunks()
            np.testing.assert_array_equal(vec[1], want_mu[new_r])
            np.testing.assert_array_equal(vec[2], want_nu[new_r])

    def test_dead_rank_without_buddy_raises(self):
        """No replicate_ring on this boundary: the serving predecessor
        must refuse loudly (silently restoring zeros into momentum is
        the failure mode the gap-check exists to prevent)."""
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)
        bs = self._boundaries(vecs, 4)
        try:
            new_workers = type(peers).of(peers[0], peers[1], peers[2])
            with pytest.raises(ValueError, match="buddy"):
                bs[2].recarve(3, peer=fakes[2], old_workers=peers,
                              new_workers=new_workers, tag="tnb",
                              dead=(3,))
        finally:
            for c in chans:
                c.close()

    def test_dead_rank_and_dead_predecessor_unrecoverable(self):
        """Ring-buddy redundancy covers single (and non-adjacent)
        failures; two ADJACENT deaths lose a chunk and must escalate to
        the checkpoint restart, loudly."""
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)
        bs = self._boundaries(vecs, 4)
        try:
            new_workers = type(peers).of(peers[0], peers[1])
            # ranks 2 AND 3 died: 3's predecessor is gone too
            with pytest.raises(ValueError, match="predecessor"):
                bs[0].recarve(2, peer=fakes[0], old_workers=peers,
                              new_workers=new_workers, tag="tdd",
                              dead=(2, 3))
        finally:
            for c in chans:
                c.close()

    def test_commit_local_validates_chunk_shape(self):
        b = ZeroBoundary()
        with pytest.raises(ValueError, match="chunk"):
            b.commit_local(0, {"mu": np.zeros(5, np.float32)},
                           total=10, old_n=4, my_old=0)

    def test_cross_slice_stride_survives_whole_slice_death(self):
        """Multislice buddies: stride = ranks_per_slice puts every
        mirror in the NEXT slice, so the demo scenario — slice 1
        (ranks 2 AND 3, adjacent) dying at once — stays recoverable.
        The same double death is exactly what
        test_dead_rank_and_dead_predecessor_unrecoverable proves fatal
        under the stride-1 adjacent ring."""
        vecs = self._vectors()
        peers, chans, fakes = _mk_world(4)
        bs = self._boundaries(vecs, 4)
        try:
            run_all([
                lambda b=b, f=f: b.replicate_ring(
                    f.channel, peers, tag="xs", stride=2)
                for b, f in zip(bs, fakes)
            ], timeout=60)
            new_workers = type(peers).of(peers[0], peers[1])
            run_all([
                lambda b=b, f=f: b.recarve(
                    2, peer=f, old_workers=peers, new_workers=new_workers,
                    tag="txs", dead=(2, 3))
                for b, f in ((bs[0], fakes[0]), (bs[1], fakes[1]))
            ], timeout=60)
        finally:
            for c in chans:
                c.close()
        want_mu = _chunks_of(vecs["mu"], self.TOTAL, 2)
        want_nu = _chunks_of(vecs["nu"], self.TOTAL, 2)
        for r in range(2):
            _, vec, _ = bs[r].chunks()
            np.testing.assert_array_equal(vec[1], want_mu[r])
            np.testing.assert_array_equal(vec[2], want_nu[r])

    def test_stride_bounds_validated(self):
        vecs = self._vectors()
        bs = self._boundaries(vecs, 4)
        for bad in (0, 4, -1):
            with pytest.raises(ValueError, match="stride"):
                bs[0].replicate_ring(None, None, tag="bad", stride=bad)


# ==========================================================================
# loud-failure gates on the exchange: step agreement, epoch agreement,
# typed timeouts, and the elastic_step grow-with-joiners guard
# ==========================================================================


class TestRecarveGuards:
    TOTAL = 10

    def _committed(self, step=5, old_n=2, my_old=0):
        b = ZeroBoundary()
        chunk = math.ceil(self.TOTAL / old_n)
        b.commit_local(step, {"mu": np.zeros(chunk, np.float32)},
                       total=self.TOTAL, old_n=old_n, my_old=my_old)
        return b

    def test_step_mismatch_raises(self):
        """A survivor one committed step ahead of the leader-agreed
        replay holds state the step-behind replay cannot use — recarve
        must refuse rather than blend two optimizer states."""
        b = ZeroBoundary()
        b.commit(5, {"mu": jnp.zeros(self.TOTAL)},
                 {"w": jnp.zeros(self.TOTAL)})
        with pytest.raises(ValueError, match="blend"):
            b.recarve(1, expect_step=4)
        # the agreed step passes, and a joiner (step -1) skips the check
        b.recarve(1, expect_step=5)

    def test_epoch_mismatch_raises(self):
        """The plan comes from the boundary's recorded geometry while
        addressing uses the caller's old_workers; a stale boundary must
        be rejected before any bytes move."""
        from kungfu_tpu.plan import PeerID, PeerList

        workers2 = PeerList.of(PeerID("127.0.0.1", 1),
                               PeerID("127.0.0.1", 2))

        class _Chan:
            def send(self, *a, **k):
                raise AssertionError("no bytes may move on a stale epoch")

            recv = send

        class _Peer:
            channel = _Chan()
            config = type("C", (), {"self_id": workers2[0]})()

        # boundary committed under 4 ranks, caller claims a 2-rank epoch
        b = self._committed(old_n=4, my_old=0)
        with pytest.raises(ValueError, match="stale"):
            b.recarve(2, peer=_Peer(), old_workers=workers2,
                      new_workers=workers2, tag="te")
        # boundary says old rank 1, old_workers places this peer at 0
        b = self._committed(old_n=2, my_old=1)
        with pytest.raises(ValueError, match="stale"):
            b.recarve(2, peer=_Peer(), old_workers=workers2,
                      new_workers=workers2, tag="te2")

    def test_recv_timeout_becomes_peer_failure_error(self):
        """A second death mid-exchange surfaces as the typed
        PeerFailureError the recovery contract promises (callers catch
        it to re-enter recovery), never a raw TimeoutError."""
        from kungfu_tpu.comm.faults import PeerFailureError
        from kungfu_tpu.plan import PeerID, PeerList

        workers = PeerList.of(PeerID("127.0.0.1", 1),
                              PeerID("127.0.0.1", 2))
        survivors = PeerList.of(workers[0])

        class _HungChan:
            def send(self, *a, **k):
                pass

            def recv(self, src, name, *a, **k):
                raise TimeoutError(f"recv {name!r} timed out")

        class _Peer:
            channel = _HungChan()
            config = type("C", (), {"self_id": workers[0]})()

        b = self._committed(old_n=2, my_old=0)
        with pytest.raises(PeerFailureError) as ei:
            b.recarve(1, peer=_Peer(), old_workers=workers,
                      new_workers=survivors, tag="tt")
        assert ei.value.rank == 1  # blame attributed to the hung old rank

    def test_elastic_step_grow_with_joiners_raises(self):
        """elastic_step cannot wire a pure joiner's side of the
        exchange (the fresh process sees changed=False); proceeding
        would strand the joiner's segments and leave it on init_opt
        zeros — it must fail loudly instead."""
        from kungfu_tpu.elastic.hooks import ElasticState, elastic_step
        from kungfu_tpu.plan import PeerID, PeerList

        old = PeerList.of(PeerID("127.0.0.1", 1), PeerID("127.0.0.1", 2))
        new = PeerList.of(PeerID("127.0.0.1", 1), PeerID("127.0.0.1", 2),
                          PeerID("127.0.0.1", 3))

        class _GrowPeer:
            cluster_version = 1
            detached = False

            def __init__(self):
                self.cluster = type("Cl", (), {"workers": old})()
                self.config = type(
                    "C", (), {"config_server": "http://stub",
                              "self_id": old[0]})()

            def chaos_rank(self):
                return 0

            def engine(self):
                return None

            def size(self):
                return len(self.cluster.workers)

            def propose_new_size(self, n):
                pass

            def resize_cluster_from_url(self):
                self.cluster.workers = new
                return True

        with pytest.raises(ValueError, match="joiner"):
            elastic_step(_GrowPeer(), ElasticState(step=0), "3:100",
                         params={}, zero_boundary=ZeroBoundary())
