"""kf-serve: the elastic inference plane (tier-1).

Covers the engine (continuous batching, greedy parity with the
full-context transformer, prefix-reuse accounting), the router
(admission, typed overload, the dead-worker/dead-slice replay ladder
over live in-process Peers), the chaos request-path clauses
(``drop_request``, ``delay:on=serve``), the serving policies, and the
kv-gauge/SLO flow through aggregator snapshots to the kftop serving
view (docs/serving.md).
"""

import time

import jax
import numpy as np
import pytest

from kungfu_tpu import chaos
from kungfu_tpu.comm.faults import RequestLostError, ServeOverloadError
from kungfu_tpu.models.transformer import Transformer, TransformerConfig
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.serve.engine import InferenceEngine
from kungfu_tpu.serve.kvcache import KVCachePool, PageSpec
from kungfu_tpu.serve.router import ServeRouter, ServeWorker

CFG = TransformerConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                        d_ff=64, max_seq=128, dtype="float32")


@pytest.fixture(scope="module")
def model_and_params():
    model = Transformer(CFG)
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(autouse=True)
def _fresh_chaos():
    chaos.reset()
    yield
    chaos.reset()


def make_engine(model_and_params, pages=128, max_batch=4, page_tokens=8,
                rank=None):
    model, params = model_and_params
    pool = KVCachePool(PageSpec.for_model(CFG, page_tokens=page_tokens),
                       capacity_pages=pages)
    return InferenceEngine(model, params, pool=pool, max_batch=max_batch,
                           max_seq=CFG.max_seq, rank=rank)


def reference_tokens(model, params, prompt, n):
    out = list(prompt)
    for _ in range(n):
        logits = model.apply(params, np.asarray([out], np.int32))
        out.append(int(np.argmax(np.asarray(logits)[0, -1])))
    return out[len(prompt):]


# -- engine -----------------------------------------------------------------
class TestEngine:
    def test_greedy_matches_full_context_reference(self, model_and_params):
        """The paged prefill/decode pair must be the SAME function as the
        training-path transformer: greedy tokens agree exactly."""
        model, params = model_and_params
        eng = make_engine(model_and_params)
        eng.submit("a", [1, 2, 3, 4, 5], 6)
        done = [e for e in eng.drain() if e["kind"] == "done"]
        assert done[0]["tokens"] == reference_tokens(
            model, params, [1, 2, 3, 4, 5], 6)

    def test_continuous_batching_admits_mid_flight(self, model_and_params):
        """A request arriving mid-decode joins the running batch at the
        next step boundary — no batch-boundary wait."""
        eng = make_engine(model_and_params)
        eng.submit("long", [1, 2, 3], 30)
        for _ in range(5):
            eng.step()
        assert eng.active_count == 1
        eng.submit("late", [9, 8], 5)
        eng.step()
        assert eng.active_count == 2  # joined while "long" still decodes
        done = {e["rid"] for e in eng.drain() if e["kind"] == "done"}
        assert done == {"long", "late"}

    def test_decode_priority_bounded_admission(self, model_and_params):
        """At most admit_per_step prefills per step: a burst of prompts
        cannot stall the decode of active requests."""
        eng = make_engine(model_and_params)
        for i in range(3):
            eng.submit(f"r{i}", [1 + i, 2, 3], 4)
        evs = eng.step()
        assert sum(e["kind"] == "admit" for e in evs) == 1
        assert eng.pending_count == 2

    def test_prefix_reuse_reduces_prefill_work(self, model_and_params):
        """The measured claim behind bench.py --serve: a shared prefix
        prefills only its un-cached suffix."""
        eng = make_engine(model_and_params)
        shared = list(range(1, 20))  # 19 tokens: 2 full pages of 8
        eng.submit("first", shared + [21], 4)
        eng.drain()
        eng.submit("second", shared + [22], 4)
        evs = eng.drain()
        adm = [e for e in evs if e["kind"] == "admit"][0]
        assert adm["reused"] == 16
        assert adm["computed"] == 4  # 20 total - 16 cached
        done = [e for e in evs if e["kind"] == "done"][0]
        assert done["reused_tokens"] == 16

    def test_reused_prefix_decodes_identically(self, model_and_params):
        """Cache-hit prefill (pages loaded, suffix computed) must produce
        the same continuation as the cold run."""
        model, params = model_and_params
        eng = make_engine(model_and_params)
        prompt = list(range(1, 18))
        eng.submit("cold", prompt, 6)
        cold = [e for e in eng.drain() if e["kind"] == "done"][0]
        eng.submit("warm", prompt, 6)
        evs = eng.drain()
        assert [e for e in evs if e["kind"] == "admit"][0]["reused"] == 16
        warm = [e for e in evs if e["kind"] == "done"][0]
        assert warm["tokens"] == cold["tokens"]

    def test_long_prompt_after_cached_prefix_stays_correct(
            self, model_and_params):
        """Regression: with a cached prefix, the padded prefill bucket
        must still FIT the slab (start + bucket(suffix) <= max_seq) —
        the overflow used to make dynamic_update_slice clamp the write
        over the restored prefix and silently corrupt the K/V (then
        commit the corruption into the prefix chain)."""
        model, params = model_and_params
        eng = make_engine(model_and_params)  # page 8, max_seq 128
        shared = list(range(1, 17))  # 2 committed pages after request A
        eng.submit("seed", shared + [30], 4)
        eng.drain()
        # B shares the prefix but its suffix bucket (128) cannot sit at
        # offset 16: admission must give the reuse back, not corrupt
        prompt_b = shared + [(31 + i) % 90 for i in range(100)]  # 116 toks
        eng.submit("long", prompt_b, 6)
        evs = eng.drain()
        adm = [e for e in evs if e["kind"] == "admit"][0]
        assert adm["reused"] + eng._prefill_bucket(116 - adm["reused"]) \
            <= eng.max_seq
        done = [e for e in evs if e["kind"] == "done"][0]
        assert done["tokens"] == reference_tokens(model, params, prompt_b, 6)

    def test_cancel_active_is_deferred_to_step_thread(self,
                                                      model_and_params):
        """cancel() of an ACTIVE request only flags it; the step thread
        retires it at the next boundary (a cross-thread release would
        race _complete's page commit)."""
        eng = make_engine(model_and_params)
        eng.submit("victim", [1, 2, 3], 30)
        eng.step()
        assert eng.active_count == 1
        held = eng.pool.stats()["live"]
        assert eng.cancel("victim") is True
        assert eng.active_count == 1  # flagged, not yet retired
        eng.step()
        assert eng.active_count == 0
        assert eng.pool.stats()["live"] < held  # pages released
        assert eng.cancel("victim") is False  # already gone

    def test_cache_exhaustion_keeps_request_pending(self, model_and_params):
        """Admission control is capacity-real: a request that cannot
        reserve its pages queues (FCFS) instead of thrashing live ones."""
        # 5 pages of 8 tokens; each request needs ceil((4+20)/8) = 3
        eng = make_engine(model_and_params, pages=5)
        eng.submit("a", [1, 2, 3, 4], 20)
        eng.submit("b", [5, 6, 7, 8], 20)
        eng.step()
        assert eng.active_count == 1 and eng.pending_count == 1
        done = [e for e in eng.drain() if e["kind"] == "done"]
        assert {e["rid"] for e in done} == {"a", "b"}

    def test_width_control(self, model_and_params):
        eng = make_engine(model_and_params, max_batch=4)
        assert eng.set_width(2) == 2
        for i in range(3):
            eng.submit(f"r{i}", [1 + i, 2], 20)
        for _ in range(4):
            eng.step()
        assert eng.active_count == 2  # width caps admission below slots
        assert eng.set_width(99) == 4  # clamped to max_batch
        eng.drain()

    def test_kv_gauge_tracks_pool(self, model_and_params):
        eng = make_engine(model_and_params)
        eng.submit("a", [1, 2, 3], 4)
        eng.step()
        assert (REGISTRY.gauge("kf_kv_cache_bytes").value
                == eng.pool.footprint_bytes > 0)
        eng.drain()


# -- chaos request-path clauses --------------------------------------------
class TestServeChaos:
    def test_spec_parses_request_clauses(self):
        clauses = chaos.parse_spec(
            "drop_request:rank=1,count=2,every=3;delay:ms=5,on=serve")
        assert [c.kind for c in clauses] == ["drop_request", "delay"]
        assert clauses[0].get("count") == 2
        assert clauses[1].get("on") == "serve"

    @pytest.mark.parametrize("bad", [
        "drop_request:peer=1",     # param not valid for kind
        "delay:on=route",          # bad on= value
    ])
    def test_junk_fails_loudly(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)

    def test_drop_request_deterministic(self, monkeypatch):
        """every=2,count=2: exactly the 2nd and 4th matching requests
        drop, on the scoped rank only — same determinism contract as
        every other clause."""
        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "drop_request:rank=1,every=2,count=2")
        ctl = chaos.controller_for(1)
        got = [ctl.on_serve_request(f"r{i}") for i in range(6)]
        assert got == [False, True, False, True, False, False]
        other = chaos.controller_for(0)
        assert not any(other.on_serve_request(f"r{i}") for i in range(4))

    def test_delay_on_serve_straggles(self, monkeypatch):
        monkeypatch.setenv("KF_CHAOS_SPEC", "delay:ms=30,on=serve,rank=0")
        ctl = chaos.controller_for(0)
        t0 = time.perf_counter()
        assert ctl.on_serve_request("r0") is False  # delayed, not dropped
        assert time.perf_counter() - t0 >= 0.025

    def test_unset_spec_is_noop(self, monkeypatch):
        monkeypatch.delenv("KF_CHAOS_SPEC", raising=False)
        assert chaos.controller_for(1) is None


# -- live router over in-process peers --------------------------------------
def make_cluster(n, base_port, monkeypatch, model_and_params,
                 worker_ranks=None, router_rank=None, commit_every=2,
                 **router_kw):
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.plan import Cluster, PeerList
    from kungfu_tpu.utils.envs import Config

    monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
    monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
    workers = PeerList.parse(
        ",".join(f"127.0.0.1:{base_port + i}" for i in range(n)))
    runners = PeerList.parse(f"127.0.0.1:{base_port + 99}")
    cluster = Cluster(runners, workers)
    peers = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
    for p in peers:
        p.start()
    router_rank = n - 1 if router_rank is None else router_rank
    worker_ranks = (worker_ranks if worker_ranks is not None
                    else [r for r in range(n) if r != router_rank])
    servers = []
    for r in worker_ranks:
        eng = make_engine(model_and_params, rank=r)
        eng.warmup(prompt_lens=(4,))
        servers.append(ServeWorker(peers[r], eng,
                                   commit_every=commit_every).start())
    router = ServeRouter(peers[router_rank], worker_ranks=worker_ranks,
                         **router_kw)
    return peers, servers, router


def teardown_cluster(peers, servers, router):
    router.close()
    for s in servers:
        if not s.dead:
            s.stop()
    for p in peers:
        try:
            p.close()
        except Exception:  # noqa: BLE001 — dead peers already closed
            pass


class TestRouterLive:
    def test_completion_and_typed_overload(self, monkeypatch,
                                           model_and_params):
        peers, servers, router = make_cluster(
            3, 26110, monkeypatch, model_and_params,
            queue_depth=2, deadline_s=10.0)
        try:
            h1 = router.submit([1, 2, 3], 30)
            h2 = router.submit([4, 5, 6], 30)
            with pytest.raises(ServeOverloadError):
                router.submit([7, 8, 9], 30)  # third in-flight > depth 2
            assert len(h1.wait(60)) == 30 and len(h2.wait(60)) == 30
            # queue drained: admission works again
            assert len(router.submit([7, 8, 9], 5).wait(60)) == 5
            assert router.completed == 3 and router.dead_workers == []
        finally:
            teardown_cluster(peers, servers, router)

    @pytest.mark.slow  # ~75s: live 3-worker cluster + chaos kill + replay
    def test_worker_kill_replays_on_survivor(self, monkeypatch,
                                             model_and_params):
        """The SLO-gated fault scenario: a chaos-killed worker's
        in-flight requests replay from their committed positions on the
        survivor, token-identical to a clean run — zero lost requests."""
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:step=6,rank=0,mode=raise")
        peers, servers, router = make_cluster(
            3, 26130, monkeypatch, model_and_params,
            deadline_s=2.0, strike_limit=2)
        model, params = model_and_params
        try:
            hs = [router.submit([9, 8, 7, i], 40) for i in range(4)]
            outs = [h.wait(90) for h in hs]
            assert all(len(o) == 40 for o in outs)
            assert router.dead_workers == [0]
            assert router.replayed >= 1 and servers[0].dead
            # replayed continuations equal the deterministic reference
            assert outs[0] == reference_tokens(model, params, [9, 8, 7, 0],
                                               40)
        finally:
            teardown_cluster(peers, servers, router)

    @pytest.mark.slow  # ~20s live cluster; flaky under full-suite load
    def test_slice_kill_excludes_whole_slice(self, monkeypatch,
                                             model_and_params):
        """die_slice kills both ranks of slice 1; the router expands the
        dead set to slice grain (training-ladder semantics) and the
        surviving slice absorbs the replays."""
        from kungfu_tpu.elastic.slices import SliceTopology

        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "die_slice:slice=1,step=6,mode=raise,rps=2")
        peers, servers, router = make_cluster(
            5, 26150, monkeypatch, model_and_params,
            worker_ranks=[0, 1, 2, 3], router_rank=4,
            deadline_s=2.0, strike_limit=1, topology=SliceTopology(2, 2))
        try:
            hs = [router.submit([3, 2, 1, i], 40) for i in range(6)]
            outs = [h.wait(120) for h in hs]
            assert all(len(o) == 40 for o in outs)
            assert router.dead_workers == [2, 3]  # the whole slice
            assert router.live_workers == [0, 1]
            assert servers[2].dead and servers[3].dead
            assert router.replayed >= 1
        finally:
            teardown_cluster(peers, servers, router)

    def test_dropped_request_replays_without_killing_worker(
            self, monkeypatch, model_and_params):
        """A chaos-dropped frame expires its deadline and replays, but a
        single strike must NOT mark the worker dead."""
        monkeypatch.setenv("KF_CHAOS_SPEC", "drop_request:count=1")
        peers, servers, router = make_cluster(
            2, 26170, monkeypatch, model_and_params,
            deadline_s=1.0, strike_limit=2)
        try:
            h = router.submit([5, 4, 3], 6)
            assert len(h.wait(60)) == 6
            assert router.replayed == 1
            assert router.dead_workers == []
        finally:
            teardown_cluster(peers, servers, router)

    def test_all_workers_dead_is_typed_loss(self, monkeypatch,
                                            model_and_params):
        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "die:step=4,rank=0,mode=raise")
        peers, servers, router = make_cluster(
            2, 26190, monkeypatch, model_and_params,
            deadline_s=1.5, strike_limit=1)
        try:
            h = router.submit([1, 2, 3], 60)
            with pytest.raises(RequestLostError) as ei:
                h.wait(60)
            assert ei.value.rid == h.rid
            assert router.live_workers == []
        finally:
            teardown_cluster(peers, servers, router)


class TestReplayBudget:
    def test_committed_eos_ends_the_request(self):
        """A committed tail ending in EOS is a finished generation:
        replay must not decode past it (the deterministic-replay
        contract would break)."""
        from kungfu_tpu.serve.router import remaining_budget

        assert remaining_budget(10, [5, 6, 2], eos_id=2) == 0
        assert remaining_budget(10, [5, 6, 2], eos_id=None) == 7
        assert remaining_budget(10, [5, 2, 6], eos_id=2) == 7  # not tail
        assert remaining_budget(10, [], eos_id=2) == 10
        assert remaining_budget(3, [1, 2, 3], eos_id=None) == 0


# -- policies ---------------------------------------------------------------
class TestServePolicies:
    def test_batch_width_controller_hysteresis(self):
        from kungfu_tpu.policy.serve import BatchWidthController
        from kungfu_tpu.serve.slo import SLOTargets

        widths = []
        ctl = BatchWidthController(
            lambda w: (widths.append(w) or w), lo=1, hi=4, start=2,
            targets=SLOTargets(e2e_s=1.0), cooldown_steps=1)
        assert ctl.width == 2
        assert ctl.observe(queued=5, e2e_ms=100.0) == 3   # widen
        assert ctl.observe(queued=5, e2e_ms=100.0) == 3   # cooldown
        assert ctl.observe(queued=5, e2e_ms=100.0) == 4
        ctl._cool = 0
        assert ctl.observe(queued=0, e2e_ms=5000.0) == 3  # SLO blown
        ctl._cool = 0
        assert ctl.observe(queued=0, e2e_ms=None) == 3    # no signal: hold

    def test_autoscale_policy_intents(self):
        from kungfu_tpu.policy.base import PolicyContext
        from kungfu_tpu.policy.serve import ServeAutoscalePolicy
        from kungfu_tpu.serve.slo import SLOTargets

        pol = ServeAutoscalePolicy(targets=SLOTargets(e2e_s=1.0),
                                   scale_up_queue=3, min_workers=1,
                                   cooldown_steps=0)
        ctx = PolicyContext(cluster_size=2)
        ctx.metrics.update(serve_queued=5, serve_e2e_ms=2500.0)
        pol.after_step(ctx)
        assert ctx.requested_size == 3  # overload: scale up
        ctx.requested_size = None
        ctx.metrics.update(serve_queued=0, serve_active=0,
                           serve_e2e_ms=50.0)
        pol.after_step(ctx)
        assert ctx.requested_size == 1  # idle: scale down
        ctx.requested_size = None
        ctx.cluster_size = 1
        pol.after_step(ctx)
        assert ctx.requested_size is None  # floored at min_workers

    def test_serve_signals_from_view(self):
        from kungfu_tpu.policy.serve import serve_signals

        assert serve_signals({"serving": None}) is None
        sig = serve_signals({"serving": {
            "active": 2, "queued": 7, "completed": 10, "rejected": 1,
            "replayed": 3, "ttft_ms": 40.0, "e2e_ms": 900.0,
            "kv_bytes": 4096}})
        assert sig["queued"] == 7 and sig["e2e_ms"] == 900.0


# -- observability flow ------------------------------------------------------
class TestServeObservability:
    def test_kv_gauge_and_slo_flow_to_cluster_view(self, model_and_params):
        """kf_kv_cache_bytes + the serve counters/histograms ride the
        existing snapshot schema into the aggregator's serving rollup —
        the same flow test kf_opt_state_bytes has."""
        from kungfu_tpu.monitor.aggregator import (ClusterAggregator,
                                                   RankReporter, field)

        eng = make_engine(model_and_params)
        eng.submit("obs", [1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
        eng.drain()
        footprint = eng.pool.footprint_bytes  # committed pages parked
        rep = RankReporter(rank=0, server_url="http://127.0.0.1:1",
                           slice_id=None)
        snap = rep.snapshot_once()
        assert field(snap, "gauges")["kf_kv_cache_bytes"] == footprint
        agg = ClusterAggregator(stale_after=60.0)
        agg.ingest(snap)
        view = agg.cluster_view()
        srv = field(view, "serving")
        assert srv is not None
        assert field(srv, "kv_bytes") == footprint
        # worker-side latency histograms rode the snapshot deltas
        lat = field(field(view, "ranks")[0], "latency")
        assert any(k.startswith("kf_serve_ttft_seconds") for k in lat)

    def test_kftop_renders_serving_section(self):
        from kungfu_tpu.monitor import kftop

        assert kftop.self_check() == 0
