"""Compose-style cluster harness (scripts/cluster.py).

The local analog of the reference's docker-compose elastic cluster CI
(``.github/workflows/cluster.yaml`` + ``benchmarks/adaptation/
gen-compose.py``): an EXTERNAL config server, one watch-mode runner per
loopback-alias host, and an elastic schedule that must grow the job onto
a host that started with zero workers and shrink away from it again.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestComposeCluster:
    def test_two_host_grow_shrink(self, tmp_path):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "scripts/cluster.py",
             "--schedule", "2:3,4:3,2:3",
             "--config-port", "9391",
             "--logdir", str(tmp_path / "logs")],
            # strictly above cluster.py's internal --timeout (420) so its
            # own rc=3 path + cleanup runs before pytest kills it
            cwd=REPO, capture_output=True, text=True, timeout=480, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["ok"] is True
        # the grow crossed onto the empty host and every size was reached
        assert out["sizes_observed"] == [2, 4]
