"""CPU-affinity partition tests (reference NUMA placement analog)."""

import os

import pytest

from kungfu_tpu.utils.affinity import (
    USE_AFFINITY,
    bind_local_rank,
    partition_cpus,
)


class TestPartition:
    def test_even_split(self):
        cpus = list(range(8))
        assert partition_cpus(cpus, 0, 2) == [0, 1, 2, 3]
        assert partition_cpus(cpus, 1, 2) == [4, 5, 6, 7]

    def test_remainder_goes_to_low_ranks(self):
        cpus = list(range(10))
        shares = [partition_cpus(cpus, r, 4) for r in range(4)]
        assert [len(s) for s in shares] == [3, 3, 2, 2]
        assert sorted(sum(shares, [])) == cpus  # exact cover, no overlap

    def test_more_ranks_than_cpus(self):
        cpus = [0, 1]
        shares = [partition_cpus(cpus, r, 4) for r in range(4)]
        assert shares == [[0], [1], [], []]

    def test_bad_args(self):
        with pytest.raises(ValueError):
            partition_cpus([0], 0, 0)
        with pytest.raises(ValueError):
            partition_cpus([0], 2, 2)


class TestBind:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv(USE_AFFINITY, raising=False)
        assert bind_local_rank(0, 1) is None

    def test_bind_and_restore(self, monkeypatch):
        monkeypatch.setenv(USE_AFFINITY, "1")
        before = os.sched_getaffinity(0)
        try:
            share = bind_local_rank(0, 1)
            assert share == sorted(before)  # whole set for a single rank
            assert os.sched_getaffinity(0) == set(share)
        finally:
            os.sched_setaffinity(0, before)

    def test_empty_share_stays_unpinned(self, monkeypatch):
        before = os.sched_getaffinity(0)
        try:
            # rank beyond the cpu count gets an empty share -> no bind
            assert bind_local_rank(len(before) + 1, len(before) + 2, force=True) is None
            assert os.sched_getaffinity(0) == before
        finally:
            os.sched_setaffinity(0, before)
