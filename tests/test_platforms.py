"""TPU-pod platform adapter (the reference's modelarts-adapter slot,
``platforms/modelarts/modelarts.go`` — scheduler env → cluster inputs)."""

import pytest

from kungfu_tpu.platforms import parse_tpu_pod_env
from kungfu_tpu.platforms.tpu_pod import detected


class TestParse:
    def test_not_a_pod(self):
        assert parse_tpu_pod_env(env={}) is None
        assert not detected(env={})

    def test_four_host_pod(self):
        env = {
            "TPU_WORKER_HOSTNAMES": "t1k-0,t1k-1,t1k-2,t1k-3",
            "TPU_WORKER_ID": "2",
        }
        info = parse_tpu_pod_env(env=env)
        assert info.num_hosts == 4
        assert info.self_host == "t1k-2" and info.worker_id == 2
        assert info.num_slices == 1 and info.coordinator == ""
        assert [h.ip for h in info.hosts.hosts] == ["t1k-0", "t1k-1", "t1k-2", "t1k-3"]
        assert all(h.slots == 1 for h in info.hosts.hosts)

    def test_multislice(self):
        env = {
            "TPU_WORKER_HOSTNAMES": "a,b",
            "TPU_WORKER_ID": "0",
            "MEGASCALE_COORDINATOR_ADDRESS": "a:8476",
            "MEGASCALE_SLICE_ID": "1",
            "MEGASCALE_NUM_SLICES": "4",
        }
        info = parse_tpu_pod_env(env=env)
        assert info.coordinator == "a:8476"
        assert info.slice_id == 1 and info.num_slices == 4

    def test_single_host_id_optional(self):
        info = parse_tpu_pod_env(env={"TPU_WORKER_HOSTNAMES": "solo"})
        assert info.worker_id == 0 and info.self_host == "solo"

    def test_missing_id_multi_host_raises(self):
        with pytest.raises(ValueError, match="TPU_WORKER_ID"):
            parse_tpu_pod_env(env={"TPU_WORKER_HOSTNAMES": "a,b"})

    def test_out_of_range_id_raises(self):
        with pytest.raises(ValueError, match="outside"):
            parse_tpu_pod_env(
                env={"TPU_WORKER_HOSTNAMES": "a,b", "TPU_WORKER_ID": "2"}
            )


class TestCliWiring:
    def test_platform_fills_topology(self, monkeypatch):
        from kungfu_tpu.runner.cli import apply_platform, build_cluster, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "1")
        ns = build_parser().parse_args(["-platform", "tpu-pod", "prog"])
        apply_platform(ns)
        assert ns.self_host == "h1" and ns.backend == "tpu" and ns.np == 2
        cluster = build_cluster(ns)
        assert cluster.size() == 2
        assert {w.host for w in cluster.workers} == {"h0", "h1"}

    def test_explicit_hosts_win_in_auto(self, monkeypatch):
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        ns = build_parser().parse_args(["-np", "2", "-H", "127.0.0.1:2", "prog"])
        apply_platform(ns)
        assert ns.hosts == "127.0.0.1:2" and ns.self_host == "127.0.0.1"

    def test_forced_platform_without_env_exits(self, monkeypatch):
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
        ns = build_parser().parse_args(["-platform", "tpu-pod", "prog"])
        with pytest.raises(SystemExit):
            apply_platform(ns)

    def test_platform_none_ignores_env(self, monkeypatch):
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        ns = build_parser().parse_args(["-platform", "none", "prog"])
        apply_platform(ns)
        assert ns.hosts == "" and ns.self_host == "127.0.0.1"

    def test_auto_oversize_np_keeps_localhost(self, monkeypatch):
        """An explicit -np the detected pod can't host (1 slot/host) opts
        out of detection — the CPU-backend test-cluster case on a TPU VM
        whose env still carries the pod contract."""
        from kungfu_tpu.runner.cli import apply_platform, build_cluster, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        ns = build_parser().parse_args(["-np", "4", "prog"])
        apply_platform(ns)
        assert ns.hosts == "" and ns.backend is None
        assert build_cluster(ns).size() == 4  # localhost:4

    def test_forced_oversize_np_exits_cleanly(self, monkeypatch):
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        ns = build_parser().parse_args(["-platform", "tpu-pod", "-np", "4", "prog"])
        with pytest.raises(SystemExit, match="exceeds the detected TPU pod"):
            apply_platform(ns)

    def test_explicit_np1_survives_detection(self, monkeypatch):
        """-np 1 given explicitly must stay 1; only the argparse default
        (None) expands to one worker per pod host."""
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        ns = build_parser().parse_args(["-np", "1", "prog"])
        apply_platform(ns)
        assert ns.np == 1 and ns.backend == "tpu"  # pod applies, np kept

        ns = build_parser().parse_args(["prog"])
        apply_platform(ns)
        assert ns.np == 2  # default expands to the pod


class _Dev:
    """Stand-in device: slice_device_groups touches only these attrs."""

    def __init__(self, slice_index=None, process_index=0):
        if slice_index is not None:
            self.slice_index = slice_index
        self.process_index = process_index

    def __repr__(self):
        return (f"Dev(s={getattr(self, 'slice_index', None)},"
                f"p={self.process_index})")


class TestSliceLayoutEdgeCases:
    """slice_device_groups / slice_mesh_layout edge cases on synthetic
    device worlds (satellite: uneven slices, contract disagreement,
    by='process' emulation fallback, single-slice passthrough)."""

    def test_groups_by_slice_index_outer_sorted(self):
        from kungfu_tpu.platforms.tpu_pod import slice_device_groups

        devs = [_Dev(slice_index=s, process_index=p)
                for s, p in ((1, 3), (0, 1), (1, 2), (0, 0))]
        groups = slice_device_groups(devs)
        assert [len(g) for g in groups] == [2, 2]
        assert {d.slice_index for d in groups[0]} == {0}
        assert {d.slice_index for d in groups[1]} == {1}

    def test_by_process_emulation_fallback(self):
        """CPU devices report no usable slice_index; the emulation
        contract regroups by process (MEGASCALE_SLICE_ID = process id)
        when the declared slice count matches THAT grouping."""
        from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

        # constant slice_index 0 (what CPU backends report) but two
        # processes: the by-slice grouping shows ONE group, the
        # process grouping shows the declared two
        devs = [_Dev(slice_index=0, process_index=p) for p in (0, 0, 1, 1)]
        flat, per = slice_mesh_layout(num_slices=2, devices=devs)
        assert per == 2 and len(flat) == 4
        assert [d.process_index for d in flat] == [0, 0, 1, 1]

    def test_contract_disagreement_fails_loudly(self):
        from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

        devs = [_Dev(slice_index=0, process_index=0) for _ in range(4)]
        with pytest.raises(ValueError, match="slice group"):
            slice_mesh_layout(num_slices=3, devices=devs)

    def test_uneven_slices_fail_loudly(self):
        from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

        devs = ([_Dev(slice_index=0)] * 3) + ([_Dev(slice_index=1)] * 1)
        with pytest.raises(ValueError, match="uneven slice sizes"):
            slice_mesh_layout(num_slices=2, devices=devs)

    def test_single_slice_passthrough(self):
        """num_slices=1 (or the env unset): one group, devices
        untouched — the byte-identical legacy path."""
        from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

        devs = [_Dev(slice_index=0, process_index=0) for _ in range(4)]
        flat, per = slice_mesh_layout(num_slices=1, devices=devs)
        assert flat == devs and per == 4

    def test_env_contract_default(self, monkeypatch):
        from kungfu_tpu.platforms.tpu_pod import slice_mesh_layout

        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        devs = [_Dev(slice_index=s) for s in (0, 0, 1, 1)]
        flat, per = slice_mesh_layout(devices=devs)  # env supplies 2
        assert per == 2
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "4")
        with pytest.raises(ValueError, match="slice group"):
            slice_mesh_layout(devices=devs)


class TestKfrunSlicePropagation:
    """kfrun propagates slice identity to workers instead of logging it:
    per-worker MEGASCALE_SLICE_ID / MEGASCALE_NUM_SLICES / KF_SLICE_RANKS
    (slice-major, the emulation contract)."""

    def _job_envs(self, argv):
        from kungfu_tpu.runner.cli import build_cluster, build_parser
        from kungfu_tpu.runner.job import Job
        from kungfu_tpu.plan import parse_strategy
        from kungfu_tpu.plan.peer import PeerID

        ns = build_parser().parse_args(argv)
        cluster = build_cluster(ns)
        job = Job(prog="prog", args=[], strategy=parse_strategy("AUTO"),
                  parent=PeerID(ns.self_host, 38080),
                  slices=max(ns.num_slices, 0))
        return [job.new_proc(w, cluster).envs for w in cluster.workers]

    def test_worker_envs_carry_slice_identity(self):
        envs_per_worker = self._job_envs(
            ["-np", "4", "-num-slices", "2", "prog"])
        assert [e["MEGASCALE_SLICE_ID"] for e in envs_per_worker] == \
            ["0", "0", "1", "1"]
        assert all(e["MEGASCALE_NUM_SLICES"] == "2" for e in envs_per_worker)
        assert all(e["KF_SLICE_RANKS"] == "2" for e in envs_per_worker)

    def test_no_slices_no_envs(self):
        envs_per_worker = self._job_envs(["-np", "2", "prog"])
        assert all("MEGASCALE_SLICE_ID" not in e for e in envs_per_worker)

    def test_respawn_after_resize_keeps_slice_geometry(self):
        """Ranks-per-slice is pinned at the FIRST spawn: a watch-mode
        respawn over a RESIZED cluster must stamp joiners with the same
        geometry the incumbents hold (slice count follows membership,
        rps never moves) — re-deriving rps from the grown size would
        split the world into divergent rank→slice maps."""
        from kungfu_tpu.plan import Cluster, PeerID, PeerList
        from kungfu_tpu.plan import parse_strategy
        from kungfu_tpu.plan.peer import PeerID as PID
        from kungfu_tpu.runner.job import Job

        def mk_cluster(n):
            return Cluster(
                PeerList.parse("127.0.0.1:38089"),
                PeerList.of(*(PeerID("127.0.0.1", 23800 + i)
                              for i in range(n))))

        job = Job(prog="prog", args=[], strategy=parse_strategy("AUTO"),
                  parent=PID("127.0.0.1", 38080), slices=2)
        c4 = mk_cluster(4)
        first = [job.new_proc(w, c4).envs for w in c4.workers]
        assert [e["MEGASCALE_SLICE_ID"] for e in first] == \
            ["0", "0", "1", "1"]
        c6 = mk_cluster(6)
        grown = [job.new_proc(w, c6).envs for w in c6.workers]
        # rps stays 2; the grown world is 3 slices of 2, not 2 of 3
        assert all(e["KF_SLICE_RANKS"] == "2" for e in grown)
        assert all(e["MEGASCALE_NUM_SLICES"] == "3" for e in grown)
        assert [e["MEGASCALE_SLICE_ID"] for e in grown] == \
            ["0", "0", "1", "1", "2", "2"]

    def test_non_tiling_np_exits(self):
        from kungfu_tpu.runner.cli import main

        with pytest.raises(SystemExit, match="does not tile"):
            main(["-np", "3", "-num-slices", "2", "prog"])

    def test_real_pod_rejects_num_slices(self, monkeypatch):
        """On a detected multislice pod, TPU_WORKER_HOSTNAMES is THIS
        slice's host list — `-num-slices` would carve one slice into
        synthetic slices and overwrite each host's true
        MEGASCALE_SLICE_ID, so it is a launch error; without the flag,
        identity passes through via the inherited env (no stamping)."""
        from kungfu_tpu.runner.cli import apply_platform, build_parser

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        ns = build_parser().parse_args(
            ["-platform", "tpu-pod", "-num-slices", "2", "prog"])
        with pytest.raises(SystemExit, match="MEGASCALE_SLICE_ID"):
            apply_platform(ns)
        ns = build_parser().parse_args(["-platform", "tpu-pod", "prog"])
        apply_platform(ns)
        assert ns.num_slices == 0  # not auto-armed: env identity wins


class TestMultislice:
    def test_single_slice_groups_and_validation(self):
        import jax

        from kungfu_tpu.platforms.tpu_pod import (multislice_communicator,
                                                  slice_device_groups)

        groups = slice_device_groups()
        assert len(groups) == 1 and len(groups[0]) == len(jax.devices())
        comm = multislice_communicator(num_slices=1)
        assert comm.size == len(jax.devices())
        import numpy as np

        x = np.arange(1, comm.size + 1, dtype=np.float32)[:, None]
        out = np.asarray(comm.all_reduce(x))
        assert float(out[0, 0]) == comm.size * (comm.size + 1) / 2
        with pytest.raises(ValueError, match="slice group"):
            multislice_communicator(num_slices=2)

    @pytest.mark.slow
    def test_two_slice_emulation_cross_slice_reduce(self):
        """Two subprocess 'slices' (one jax process each, 2 CPU devices,
        MEGASCALE_* contract set): the hierarchical two_stage reduce over
        the (slice, within-slice) mesh must match the flat psum."""
        import os
        import socket
        import subprocess
        import sys
        import time

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        child = (
            "import sys, os, numpy as np\n"
            f"sys.path.insert(0, {repo!r})\n"
            "import jax\n"
            "from kungfu_tpu.utils.jaxcompat import set_cpu_device_count\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "set_cpu_device_count(2)\n"
            "jax.config.update('jax_cpu_collectives_implementation', 'gloo')\n"
            "rank, port = int(sys.argv[1]), int(sys.argv[2])\n"
            "jax.distributed.initialize(f'127.0.0.1:{port}', 2, rank)\n"
            "from kungfu_tpu.platforms.tpu_pod import multislice_communicator\n"
            "comm = multislice_communicator()  # MEGASCALE_NUM_SLICES env\n"
            "assert comm.size == 4 and comm.num_hosts == 2, comm\n"
            "x = np.full((comm.addressable_n, 1), float(rank + 1), np.float32)\n"
            "flat = np.asarray(comm.all_reduce(x))          # psum\n"
            "comm.set_strategy('two_stage')\n"
            "hier = np.asarray(comm.all_reduce(x))          # DCN-shaped\n"
            "assert float(flat[0, 0]) == 6.0, flat\n"
            "assert np.array_equal(flat, hier), (flat, hier)\n"
            "# the cross-slice stage alone reduces over the OUTER axis\n"
            "cross = np.asarray(comm.cross_all_reduce(x))\n"
            "assert float(cross[0, 0]) == 3.0, cross\n"
            "print(f'MULTISLICE_OK rank={rank}')\n"
        )
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["MEGASCALE_NUM_SLICES"] = "2"
        env["MEGASCALE_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", child, str(r), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env={**env, "MEGASCALE_SLICE_ID": str(r)},
            )
            for r in range(2)
        ]
        deadline = time.monotonic() + 180.0
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic()))
                outs.append(out)
                assert p.returncode == 0, out
            assert all("MULTISLICE_OK" in o for o in outs), outs
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
