"""Slice-aware fault tolerance: topology, verdict/quorum, and the
in-process slice-granular shrink ladder (docs/multislice.md).

The in-process cluster trick is the same as tests/test_chaos.py: real
``Peer`` objects on loopback with the python transport, multislice
armed through the env contract (``MEGASCALE_NUM_SLICES`` +
``KF_SLICE_RANKS``), chaos ``die_slice`` in ``mode=raise`` standing in
for whole-slice process death."""

import threading

import numpy as np
import pytest

from kungfu_tpu import chaos
from kungfu_tpu.checkpoint import StepSnapshot
from kungfu_tpu.comm.faults import (PeerFailureError, QuorumLostError,
                                    SliceExcludedError)
from kungfu_tpu.elastic.slices import (SliceTopology, align_to_slices,
                                       bootstrap_topology, slice_quorum_ok,
                                       slice_verdict)
from kungfu_tpu.plan import Cluster, PeerID, PeerList, Strategy

from tests._util import run_all


@pytest.fixture(autouse=True)
def _fresh_chaos():
    chaos.reset()
    yield
    chaos.reset()


def make_slice_peers(n, num_slices, base_port, monkeypatch):
    """n real Peers on loopback with the multislice env contract armed
    (slice-major: rank r lives in slice r // (n / num_slices))."""
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.utils.envs import Config

    monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", str(num_slices))
    monkeypatch.setenv("KF_SLICE_RANKS", str(n // num_slices))
    workers = PeerList.of(
        *(PeerID("127.0.0.1", base_port + i) for i in range(n)))
    runners = PeerList.parse("127.0.0.1:38089")
    cluster = Cluster(runners, workers)
    peers = [
        Peer(Config(self_id=workers[i], cluster=cluster,
                    strategy=Strategy.STAR))
        for i in range(n)
    ]
    for p in peers:
        p.start()
    return workers, peers


class TestTopology:
    def test_mapping_and_leaders(self):
        t = SliceTopology(3, 2)
        assert [t.slice_of(r) for r in range(6)] == [0, 0, 1, 1, 2, 2]
        assert t.ranks_in(1) == [2, 3]
        assert t.leader_of(2) == 4
        assert t.size == 6

    def test_for_size_keeps_rps_and_rejects_fractions(self):
        t = SliceTopology(2, 2)
        assert t.for_size(2) == SliceTopology(1, 2)
        with pytest.raises(ValueError, match="whole slices"):
            t.for_size(3)

    def test_bootstrap_from_env(self, monkeypatch):
        monkeypatch.delenv("MEGASCALE_NUM_SLICES", raising=False)
        monkeypatch.delenv("KF_SLICE_RANKS", raising=False)
        assert bootstrap_topology(4) is None  # single slice: legacy path
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        assert bootstrap_topology(4) == SliceTopology(2, 2)
        # the pinned launcher value wins over derivation
        monkeypatch.setenv("KF_SLICE_RANKS", "3")
        assert bootstrap_topology(4) == SliceTopology(2, 3)
        # without the pin, a non-tiling worker count fails loudly
        monkeypatch.delenv("KF_SLICE_RANKS")
        with pytest.raises(ValueError, match="tile"):
            bootstrap_topology(5)

    def test_align_to_slices(self):
        t = SliceTopology(4, 2)
        assert align_to_slices(5, t) == 6
        assert align_to_slices(6, t) == 6
        assert align_to_slices(0, t) == 2  # never below one slice


class TestVerdictAndQuorum:
    def test_verdict_splits_dead_and_degraded(self):
        t = SliceTopology(3, 2)
        dead, degraded = slice_verdict([2, 3, 4], t)
        assert dead == {1} and degraded == {2}

    def test_quorum_strict_majority(self):
        t = SliceTopology(3, 1)
        assert slice_quorum_ok([0, 2], t)
        assert not slice_quorum_ok([2], t)

    def test_quorum_half_tiebreak_on_lowest_slice(self):
        """Exactly half survives: ONLY the side holding slice 0 may
        continue — a partition's two halves are disjoint, so both
        cannot.  This is what makes a 2-slice pod's slice loss
        survivable where rank-granular strict majority refuses."""
        t = SliceTopology(2, 2)
        assert slice_quorum_ok([0], t)
        assert not slice_quorum_ok([1], t)
        t4 = SliceTopology(4, 1)
        assert slice_quorum_ok([0, 3], t4)
        assert not slice_quorum_ok([1, 2], t4)


class TestPeerWiring:
    def test_single_slice_is_byte_identical(self, monkeypatch):
        """No MEGASCALE contract -> no topology, psum default strategy:
        today's behavior, untouched."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        monkeypatch.delenv("MEGASCALE_NUM_SLICES", raising=False)
        workers = PeerList.parse("127.0.0.1:24990")
        p = Peer(Config(self_id=workers[0],
                        cluster=Cluster(PeerList.parse("127.0.0.1:38089"),
                                        workers)))
        assert p.slice_topology() is None
        assert p._comm_strategy == "psum"

    def test_multislice_defaults_to_two_stage(self, monkeypatch):
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        monkeypatch.setenv("KF_SLICE_RANKS", "1")
        workers = PeerList.parse("127.0.0.1:24991,127.0.0.1:24992")
        p = Peer(Config(self_id=workers[0],
                        cluster=Cluster(PeerList.parse("127.0.0.1:38089"),
                                        workers)))
        topo = p.slice_topology()
        assert topo == SliceTopology(2, 1)
        assert p.slice_id() == 0
        assert p._comm_strategy == "two_stage"
        # an explicit user choice still wins over the multislice default
        p2 = Peer(Config(self_id=workers[0],
                         cluster=Cluster(PeerList.parse("127.0.0.1:38089"),
                                         workers),
                         device_strategy="ring"))
        assert p2._comm_strategy == "ring"

    def test_incoherent_inherited_contract_runs_flat(self, monkeypatch):
        """A pod host's inherited MEGASCALE_NUM_SLICES with a worker
        world that does not tile it (and no launcher-pinned
        KF_SLICE_RANKS) must not crash kf.init() — it logs and runs
        single-slice, the pre-multislice behavior."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        monkeypatch.delenv("KF_SLICE_RANKS", raising=False)
        workers = PeerList.parse(
            "127.0.0.1:24993,127.0.0.1:24994,127.0.0.1:24995")
        p = Peer(Config(self_id=workers[0],
                        cluster=Cluster(PeerList.parse("127.0.0.1:38089"),
                                        workers)))
        assert p.slice_topology() is None
        assert p._comm_strategy == "psum"

    def test_resize_alignment(self, monkeypatch):
        from kungfu_tpu.elastic.resize import slice_aligned_size

        class _P:
            def slice_topology(self):
                return SliceTopology(2, 2)

        assert slice_aligned_size(_P(), 3) == 4
        assert slice_aligned_size(_P(), 1) == 2
        assert slice_aligned_size(_P(), 4) == 4

        class _Single:
            def slice_topology(self):
                return None

        assert slice_aligned_size(_Single(), 3) == 3


class TestSliceShrink:
    """The tentpole ladder, in-process: 2 slices x 2 ranks, slice 1
    dies whole, slice 0 survives the slice-granular quorum that
    rank-granular strict majority (2*2 <= 4) would have refused."""

    def test_whole_slice_death_shrinks_to_surviving_slice(self, monkeypatch):
        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "die_slice:slice=1,coll=2,mode=raise,rps=2")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_slice_peers(4, 2, 26700, monkeypatch)
        data = [np.arange(16, dtype=np.float32) * (i + 1) for i in range(4)]
        snaps = [StepSnapshot() for _ in range(4)]
        try:
            outs = run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            for i, o in enumerate(outs):
                assert np.array_equal(o, sum(data))
                snaps[i].commit(1, {"w": o})

            results = [None] * 4

            def victim(i):
                try:
                    peers[i].engine().all_reduce(data[i], name="s2")
                    results[i] = ("no-death", None)
                except chaos.InjectedDeath:
                    peers[i].close()
                    results[i] = ("died", None)

            def survivor(i):
                try:
                    out = peers[i].engine().all_reduce(data[i], name="s2")
                    results[i] = ("clean", out)
                    return
                except PeerFailureError as err:
                    shrunk, replay = peers[i].recover_from_failure(
                        err, snapshot=snaps[i])
                    assert shrunk, "surviving slice must agree to shrink"
                    assert replay is not None and replay[0] == 1
                    out = peers[i].engine().all_reduce(data[i], name="s2r")
                    results[i] = ("recovered", out)

            ts = ([threading.Thread(target=victim, args=(i,), daemon=True)
                   for i in (2, 3)]
                  + [threading.Thread(target=survivor, args=(i,), daemon=True)
                     for i in (0, 1)])
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts), "recovery hung"

            assert results[2][0] == "died" and results[3][0] == "died"
            want = data[0] + data[1]
            for i in (0, 1):
                status, out = results[i]
                assert status == "recovered", results[i]
                assert np.array_equal(out, want)
                assert peers[i].size() == 2
                # the DCN topology re-carved: one slice remains
                assert peers[i].slice_topology() == SliceTopology(1, 2)
                assert not peers[i].detached
        finally:
            for i in (0, 1):
                peers[i].close()

    def test_partial_slice_death_excludes_the_whole_slice(self, monkeypatch):
        """Only rank 2 dies: its slice-mate rank 3 is ALIVE, answers
        ping — and must stand down (SliceExcludedError), while slice 0
        excludes the whole slice."""
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=2,rank=2,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_slice_peers(4, 2, 26720, monkeypatch)
        data = [np.ones(8, np.float32) * (i + 1) for i in range(4)]
        snaps = [StepSnapshot() for _ in range(4)]
        try:
            outs = run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            for i, o in enumerate(outs):
                snaps[i].commit(1, {"w": o})
            results = [None] * 4

            def victim():
                try:
                    peers[2].engine().all_reduce(data[2], name="s2")
                except chaos.InjectedDeath:
                    peers[2].close()
                    results[2] = ("died", None)

            def excluded():
                try:
                    peers[3].engine().all_reduce(data[3], name="s2")
                    results[3] = ("clean", None)
                except PeerFailureError as err:
                    try:
                        peers[3].recover_from_failure(err, snapshot=snaps[3])
                        results[3] = ("shrunk", None)
                    except SliceExcludedError as exc:
                        assert exc.slice_id == 1
                        results[3] = ("excluded", exc)

            def survivor(i):
                try:
                    peers[i].engine().all_reduce(data[i], name="s2")
                    results[i] = ("clean", None)
                except PeerFailureError as err:
                    shrunk, replay = peers[i].recover_from_failure(
                        err, snapshot=snaps[i])
                    assert shrunk and replay[0] == 1
                    results[i] = ("recovered", None)

            ts = ([threading.Thread(target=victim, daemon=True),
                   threading.Thread(target=excluded, daemon=True)]
                  + [threading.Thread(target=survivor, args=(i,), daemon=True)
                     for i in (0, 1)])
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts), "recovery hung"

            assert results[2][0] == "died"
            assert results[3][0] == "excluded", results[3]
            for i in (0, 1):
                assert results[i][0] == "recovered", results[i]
                assert peers[i].size() == 2
                # the ALIVE rank 3 was excluded along with its dead mate
                assert peers[i].cluster.workers.rank(workers[3]) is None
        finally:
            for i in (0, 1, 3):
                peers[i].close()

    def test_losing_slice_zero_loses_quorum(self, monkeypatch):
        """The other half of the tie-break: survivors WITHOUT slice 0
        must refuse (exactly-half, no lowest slice) and escalate."""
        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "die_slice:slice=0,coll=2,mode=raise,rps=1")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_slice_peers(2, 2, 26740, monkeypatch)
        data = [np.ones(4, np.float32) * (i + 1) for i in range(2)]
        try:
            run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            results = [None] * 2

            def victim():
                try:
                    peers[0].engine().all_reduce(data[0], name="s2")
                except chaos.InjectedDeath:
                    peers[0].close()
                    results[0] = ("died", None)

            def survivor():
                try:
                    peers[1].engine().all_reduce(data[1], name="s2")
                    results[1] = ("clean", None)
                except PeerFailureError as err:
                    try:
                        peers[1].recover_from_failure(err)
                        results[1] = ("shrunk", None)
                    except QuorumLostError as q:
                        results[1] = ("quorum-lost", q)

            ts = [threading.Thread(target=victim, daemon=True),
                  threading.Thread(target=survivor, daemon=True)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts)
            assert results[0][0] == "died"
            assert results[1][0] == "quorum-lost", results[1]
        finally:
            peers[1].close()


class TestLastSliceRankGrain:
    """Once a job is down to ONE slice there is no cross-slice mesh
    left to protect: a single rank death must run the CLASSIC rank
    ladder (3-of-? strict majority shrink), not exclude the lone
    remaining slice and halt everything."""

    def test_rank_death_on_last_slice_shrinks_by_rank(self, monkeypatch):
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        # the post-slice-shrink state, bootstrapped directly: a 2-slice
        # contract (rps pinned to 3) whose CURRENT membership is one
        # whole slice of 3 — slice_topology() == (1, 3)
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        monkeypatch.setenv("KF_SLICE_RANKS", "3")
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=2,rank=2,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers = PeerList.of(
            *(PeerID("127.0.0.1", 26760 + i) for i in range(3)))
        cluster = Cluster(PeerList.parse("127.0.0.1:38089"), workers)
        peers = [Peer(Config(self_id=workers[i], cluster=cluster,
                             strategy=Strategy.STAR)) for i in range(3)]
        for p in peers:
            p.start()
        assert peers[0].slice_topology() is not None
        assert peers[0].slice_topology().num_slices == 1
        data = [np.ones(8, np.float32) * (i + 1) for i in range(3)]
        snaps = [StepSnapshot() for _ in range(3)]
        try:
            outs = run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            for i, o in enumerate(outs):
                snaps[i].commit(1, {"w": o})
            results = [None] * 3

            def victim():
                try:
                    peers[2].engine().all_reduce(data[2], name="s2")
                except chaos.InjectedDeath:
                    peers[2].close()
                    results[2] = ("died", None)

            def survivor(i):
                try:
                    peers[i].engine().all_reduce(data[i], name="s2")
                    results[i] = ("clean", None)
                except PeerFailureError as err:
                    # rank grain: NOT SliceExcludedError — 2-of-3 is a
                    # strict majority and the job keeps training
                    shrunk, replay = peers[i].recover_from_failure(
                        err, snapshot=snaps[i])
                    assert shrunk and replay[0] == 1
                    results[i] = ("recovered", None)

            ts = ([threading.Thread(target=victim, daemon=True)]
                  + [threading.Thread(target=survivor, args=(i,), daemon=True)
                     for i in (0, 1)])
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts), "recovery hung"
            assert results[2][0] == "died"
            for i in (0, 1):
                assert results[i][0] == "recovered", results[i]
                assert peers[i].size() == 2
                # 2 workers no longer tile 3-rank slices: slice
                # semantics are over for good
                assert peers[i].slice_topology() is None
        finally:
            for i in (0, 1):
                peers[i].close()


class TestReporterSliceIdentity:
    def test_explicit_none_beats_env(self, monkeypatch):
        """A Peer that rejected an incoherent MEGASCALE contract passes
        slice_id=None — authoritative: the env must not resurrect slice
        rows (false kftop SLICE LOSS alarms on a rank-granular job)."""
        from kungfu_tpu.monitor.aggregator import RankReporter

        monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        r = RankReporter(0, "http://127.0.0.1:1", slice_id=None)
        assert r.slice_id is None
        assert RankReporter(0, "http://127.0.0.1:1").slice_id == 1
        assert RankReporter(0, "http://127.0.0.1:1", slice_id=3).slice_id == 3

    def test_malformed_env_means_no_slice(self, monkeypatch):
        from kungfu_tpu.monitor.aggregator import RankReporter

        monkeypatch.setenv("MEGASCALE_SLICE_ID", "0")
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "two")
        assert RankReporter(0, "http://127.0.0.1:1").slice_id is None
