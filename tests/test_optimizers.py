"""Distributed optimizer semantics tests.

Simulated peers = leading stacked axis shard-mapped over the 8-device CPU
mesh (analog of the reference's np=4 localhost optimizer tests,
tests/python/integration/test_optimizers.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from kungfu_tpu.comm import Communicator
from kungfu_tpu.optimizers import (
    adaptive_sgd,
    monitor_gradient_noise_scale,
    monitor_gradient_variance,
    synchronous_averaging,
    synchronous_sgd,
)
from kungfu_tpu.utils.jaxcompat import shard_map

N = 8


@pytest.fixture(scope="module")
def comm():
    return Communicator()


def per_peer(comm, fn):
    """shard_map a per-peer function over stacked inputs."""
    return jax.jit(
        shard_map(
            fn,
            mesh=comm.mesh,
            in_specs=P(comm.axis),
            out_specs=P(comm.axis),
        )
    )


def stacked(shape, seed=0):
    return np.random.RandomState(seed).uniform(-1, 1, (N,) + shape).astype(np.float32)


class TestSyncSGD:
    def test_equals_mean_gradient_sgd(self, comm):
        lr = 0.1
        params0 = stacked((4,))
        grads = stacked((4,), seed=1)
        opt = synchronous_sgd(optax.sgd(lr), axis=comm.axis)

        def step(p, g):
            state = opt.init(p)
            updates, _ = opt.update(g, state, p)
            return optax.apply_updates(p, updates)

        out = np.asarray(per_peer(comm, step)(params0, grads))
        want = params0 - lr * np.broadcast_to(grads.mean(0), grads.shape)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_sum_mode(self, comm):
        params0 = stacked((3,))
        grads = stacked((3,), seed=2)
        opt = synchronous_sgd(optax.sgd(1.0), axis=comm.axis, average=False)

        def step(p, g):
            updates, _ = opt.update(g, opt.init(p), p)
            return optax.apply_updates(p, updates)

        out = np.asarray(per_peer(comm, step)(params0, grads))
        np.testing.assert_allclose(out, params0 - grads.sum(0), rtol=1e-5)

    def test_fused_buckets_match_per_leaf(self, comm):
        """fuse_grads=True (one flat-buffer collective) must be
        value-identical to the per-leaf path, mixed shapes and dtypes
        included, on every schedule."""
        lr = 0.1
        tree_p = {
            "w": stacked((4, 3)),
            "b": stacked((3,), seed=5),
        }
        tree_g = {
            "w": stacked((4, 3), seed=6),
            "b": stacked((3,), seed=7),
        }
        for sched in ("psum", "ring", "two_stage"):
            outs = {}
            for fused in (False, True):
                opt = synchronous_sgd(optax.sgd(lr), axis=comm.axis,
                                      schedule=sched, fuse_grads=fused)

                def step(p, g):
                    updates, _ = opt.update(g, opt.init(p), p)
                    return optax.apply_updates(p, updates)

                outs[fused] = per_peer(comm, step)(tree_p, tree_g)
            for a, b in zip(jax.tree_util.tree_leaves(outs[False]),
                            jax.tree_util.tree_leaves(outs[True])):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-7,
                                           err_msg=sched)

    def test_replicas_stay_in_sync(self, comm):
        """After a sync step from identical params, replicas are identical."""
        p0 = np.broadcast_to(np.arange(4, dtype=np.float32), (N, 4)).copy()
        grads = stacked((4,), seed=3)
        opt = synchronous_sgd(optax.adam(1e-2), axis=comm.axis)

        def step(p, g):
            updates, _ = opt.update(g, opt.init(p), p)
            return optax.apply_updates(p, updates)

        out = np.asarray(per_peer(comm, step)(p0, grads))
        for i in range(1, N):
            np.testing.assert_allclose(out[i], out[0], rtol=1e-6)


class TestSMA:
    def test_ea_sgd_update(self, comm):
        lr, alpha = 0.1, 0.1
        params0 = stacked((4,))
        grads = stacked((4,), seed=1)
        opt = synchronous_averaging(optax.sgd(lr), axis=comm.axis, alpha=alpha)

        def step(p, g):
            updates, _ = opt.update(g, opt.init(p), p)
            return optax.apply_updates(p, updates)

        out = np.asarray(per_peer(comm, step)(params0, grads))
        avg = params0.mean(0)
        want = params0 - lr * grads + alpha * (avg - params0)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_contracts_toward_consensus(self, comm):
        """With zero gradients, repeated SMA shrinks replica disagreement."""
        opt = synchronous_averaging(optax.sgd(0.1), axis=comm.axis, alpha=0.5)
        p = stacked((4,))
        zeros = np.zeros_like(p)

        def step(p, g):
            updates, _ = opt.update(g, opt.init(p), p)
            return optax.apply_updates(p, updates)

        f = per_peer(comm, step)
        spread0 = p.std(0).mean()
        for _ in range(5):
            p = np.asarray(f(p, zeros))
        assert p.std(0).mean() < 0.05 * spread0


class TestAdaptiveSGD:
    def test_phase_switch(self, comm):
        lr, alpha, change = 0.1, 0.1, 2
        opt = adaptive_sgd(optax.sgd(lr), axis=comm.axis, change_step=change, alpha=alpha)
        params0 = stacked((4,))
        grads = stacked((4,), seed=1)

        def steps(p, g):
            state = opt.init(p)
            outs = []
            for _ in range(4):
                updates, state = opt.update(g, state, p)
                p = optax.apply_updates(p, updates)
                outs.append(p)
            return tuple(outs)

        outs = per_peer(comm, steps)(params0, grads)
        outs = [np.asarray(o) for o in outs]
        # step 0 (SMA phase): local grads + alpha pull
        avg0 = params0.mean(0)
        want0 = params0 - lr * grads + alpha * (avg0 - params0)
        np.testing.assert_allclose(outs[0], want0, rtol=1e-4)
        # after the switch step, replicas are re-synced and move together
        post = outs[2]
        for i in range(1, N):
            np.testing.assert_allclose(post[i], post[0], rtol=1e-4, atol=1e-6)
        # and stay together under sync updates
        final = outs[3]
        for i in range(1, N):
            np.testing.assert_allclose(final[i], final[0], rtol=1e-4, atol=1e-6)


class TestMonitors:
    def test_gns_state_updates(self, comm):
        opt = monitor_gradient_noise_scale(
            optax.sgd(0.1), axis=comm.axis, local_batch_size=32
        )
        params0 = stacked((6,))
        grads = stacked((6,), seed=1)

        def step(p, g):
            state = opt.init(p)
            updates, state = opt.update(g, state, p)
            return optax.apply_updates(p, updates), state.noise_scale[None]

        newp, gns = per_peer(comm, step)(params0, grads)
        gns = np.asarray(gns)
        assert np.all(np.isfinite(gns))
        # identical grads across peers -> zero noise -> GNS ~ 0
        same = np.broadcast_to(grads[0], grads.shape).copy()
        _, gns0 = per_peer(comm, step)(params0, same)
        assert abs(float(np.asarray(gns0)[0])) < 1e-3

    def test_variance_zero_for_identical_grads(self, comm):
        opt = monitor_gradient_variance(optax.sgd(0.1), axis=comm.axis)
        params0 = stacked((5,))
        same = np.broadcast_to(params0[0], params0.shape).copy()

        def step(p, g):
            updates, state = opt.update(g, opt.init(p), p)
            return optax.apply_updates(p, updates), state.variance[None]

        _, var_same = per_peer(comm, step)(params0, same)
        assert float(np.asarray(var_same)[0]) < 1e-6
        diff = stacked((5,), seed=9)
        _, var_diff = per_peer(comm, step)(params0, diff)
        assert float(np.asarray(var_diff)[0]) > 1e-3

    def test_variance_matches_numpy(self, comm):
        """Exactness vs the definition E_i |g_i - g_avg|^2 computed in
        numpy — transcription errors in cross-replica statistics are
        invisible to zero/nonzero smoke checks (the sync-BN variance bug
        shipped through exactly that gap)."""
        from kungfu_tpu.ops.monitor import group_all_reduce_with_variance

        grads = stacked((7,), seed=3)

        def f(g):
            avg, var = group_all_reduce_with_variance(g, comm.axis)
            return avg, var[None]

        avg, var = per_peer(comm, f)(grads)
        want_avg = grads.mean(axis=0)
        want_var = np.mean([np.sum((g - want_avg) ** 2) for g in grads])
        np.testing.assert_allclose(np.asarray(avg)[0], want_avg, rtol=1e-5)
        np.testing.assert_allclose(float(np.asarray(var)[0]), want_var, rtol=1e-4)

    def test_gns_matches_formula(self, comm):
        """Exactness vs the two-batch estimator (OpenAI GNS appendix):
        |G|^2 = (B|g_B|^2 - b|g_b|^2)/(B - b), S = (|g_b|^2 - |g_B|^2) /
        (1/b - 1/B), GNS = S/|G|^2, with |g_b|^2 peer-averaged."""
        from kungfu_tpu.ops.monitor import global_noise_scale

        b_small = 16
        grads = stacked((9,), seed=4)

        def gns_fn(g):
            import kungfu_tpu.ops.collective as kc
            avg = kc.all_reduce(g, comm.axis, op="mean")
            return global_noise_scale(g, avg, b_small, comm.axis)[None]

        got = float(np.asarray(per_peer(comm, gns_fn)(grads))[0])

        n = grads.shape[0]
        b_big = b_small * n
        avg = grads.mean(axis=0)
        g_small_sq = np.mean([np.sum(g * g) for g in grads])
        g_big_sq = np.sum(avg * avg)
        g2 = (b_big * g_big_sq - b_small * g_small_sq) / (b_big - b_small)
        s = (g_small_sq - g_big_sq) / (1.0 / b_small - 1.0 / b_big)
        want = s / abs(g2)
        np.testing.assert_allclose(got, want, rtol=1e-4)


class TestPairAveraging:
    def test_single_process_gossip_loop(self):
        """np=1 degenerate mode: behaves like plain SGD, publishes models."""
        from kungfu_tpu.optimizers import PairAveragingOptimizer
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.store.store import reset_local_store

        reset_local_store()
        peer = Peer()  # single-process config
        peer.start()
        opt = PairAveragingOptimizer(optax.sgd(0.1), peer=peer)
        params = {"w": jnp.arange(4, dtype=jnp.float32)}
        state = opt.init(params)
        grads = {"w": jnp.ones(4, jnp.float32)}
        params, state = opt.step(params, grads, state)
        np.testing.assert_allclose(
            np.asarray(params["w"]), np.arange(4) - 0.1, rtol=1e-6
        )
        # model was published to the peer's store
        assert peer.store.get("model") is not None
        reset_local_store()

    def test_two_peer_gossip_averaging(self):
        """Two in-process peers with real TCP channels: pull + average."""
        from kungfu_tpu.optimizers import PairAveragingOptimizer
        from kungfu_tpu.plan import Cluster, PeerID, PeerList
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.store.store import reset_local_store
        from kungfu_tpu.utils.envs import Config

        reset_local_store()
        workers = PeerList.parse("127.0.0.1:24001,127.0.0.1:24002")
        runners = PeerList.parse("127.0.0.1:38081")
        cluster = Cluster(runners, workers)
        peers = [
            Peer(Config(self_id=workers[i], cluster=cluster))
            for i in range(2)
        ]
        for p in peers:
            p.start()
        try:
            opts = [
                PairAveragingOptimizer(optax.sgd(0.0), peer=p, selector="roundrobin")
                for p in peers
            ]
            params = [
                {"w": jnp.zeros(4, jnp.float32)},
                {"w": jnp.ones(4, jnp.float32) * 2.0},
            ]
            import threading

            states = [None, None]

            def init_one(i):
                states[i] = opts[i].init(params[i])

            ts = [threading.Thread(target=init_one, args=(i,)) for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            grads = {"w": jnp.zeros(4, jnp.float32)}
            # peer 0 pulls peer 1's model (2.0) and averages -> 1.0
            params0, _ = opts[0].step(params[0], grads, states[0])
            np.testing.assert_allclose(np.asarray(params0["w"]), np.ones(4), rtol=1e-6)
        finally:
            for p in peers:
                p.close()
            reset_local_store()


class _FakePullPeer:
    """Drives _ModelPuller without a wire: request_into fills the buffer
    with an incrementing fill value, or misses when told to."""

    def __init__(self):
        self.pulls = 0
        self.miss = False
        self.delay = 0.0

    def request_into(self, target, name, buf, version=None, timeout=None,
                     send_retries=None):
        import time

        if self.delay:
            time.sleep(self.delay)
        if self.miss:
            return None
        self.pulls += 1
        buf[:] = float(self.pulls)
        return buf


class TestAsyncPairAveraging:
    def _puller(self, peer, **kw):
        from kungfu_tpu.optimizers.async_sgd import _ModelPuller

        kw.setdefault("min_interval", 0.0)
        return _ModelPuller(peer, "m", 32, lambda: 1, **kw)

    def test_puller_lands_and_reuses(self):
        import time

        peer = _FakePullPeer()
        p = self._puller(peer, min_interval=60.0)  # exactly one landing
        p.start()
        try:
            assert p.wait_landed(5.0)
            buf, seq = p.take()
            assert seq == 1
            np.testing.assert_allclose(buf, 1.0)
            # no new landing: take() reuses the same model + seq
            buf2, seq2 = p.take()
            assert seq2 == 1 and buf2 is buf
        finally:
            p.close()
        assert not p.is_alive()

    def test_puller_freshest_wins(self):
        import time

        peer = _FakePullPeer()
        p = self._puller(peer)
        p.start()
        try:
            assert p.wait_landed(5.0)
            deadline = time.monotonic() + 5.0
            while peer.pulls < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
            buf, seq = p.take()
            assert seq >= 2  # skipped straight to the freshest landing
            later_buf, later_seq = p.take()
            assert later_seq >= seq
        finally:
            p.close()

    def test_puller_miss_path(self):
        peer = _FakePullPeer()
        peer.miss = True
        p = self._puller(peer)
        p.start()
        try:
            assert not p.wait_landed(0.3)
            assert p.take() is None
            assert p.misses > 0
        finally:
            p.close()
        assert not p.is_alive()

    def test_puller_teardown_with_slow_wire(self):
        """close() returns promptly even with a pull in flight."""
        import time

        peer = _FakePullPeer()
        peer.delay = 0.5
        p = self._puller(peer, pull_timeout=1.0)
        p.start()
        t0 = time.monotonic()
        p.close()
        assert time.monotonic() - t0 < 5.0
        assert not p.is_alive()

    def test_two_peer_async_gossip_averaging(self):
        """Real TCP channels: the background pull lands and the step
        averages with it off the critical path."""
        from kungfu_tpu.optimizers import AsyncPairAveragingOptimizer
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.store.store import reset_local_store
        from kungfu_tpu.utils.envs import Config

        reset_local_store()
        workers = PeerList.parse("127.0.0.1:24011,127.0.0.1:24012")
        runners = PeerList.parse("127.0.0.1:38082")
        cluster = Cluster(runners, workers)
        peers = [Peer(Config(self_id=workers[i], cluster=cluster))
                 for i in range(2)]
        for p in peers:
            p.start()
        opts = []
        try:
            opts = [AsyncPairAveragingOptimizer(
                optax.sgd(0.0), peer=p, selector="roundrobin",
                pull_timeout=10.0) for p in peers]
            params = [
                {"w": jnp.zeros(4, jnp.float32)},
                {"w": jnp.ones(4, jnp.float32) * 2.0},
            ]
            import threading

            states = [None, None]

            def init_one(i):
                states[i] = opts[i].init(params[i])

            ts = [threading.Thread(target=init_one, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            grads = {"w": jnp.zeros(4, jnp.float32)}
            # first step blocks for the first landing (reference
            # semantics), so the average is deterministic: 0.5*(0+2)=1
            params0, _ = opts[0].step(params[0], grads, states[0])
            np.testing.assert_allclose(np.asarray(params0["w"]),
                                       np.ones(4), rtol=1e-6)
            assert opts[0].averaged_steps == 1
            assert opts[0].pull_bytes >= 16
        finally:
            for o in opts:
                o.close()
            for p in peers:
                p.close()
            reset_local_store()

    def test_staleness_bound_blocks_for_fresh_landing(self):
        """After max_staleness consumptions of one landing, the step
        waits (bounded) for a fresh one instead of diverging."""
        from kungfu_tpu.optimizers.async_sgd import AsyncPairAveragingOptimizer

        opt = AsyncPairAveragingOptimizer.__new__(AsyncPairAveragingOptimizer)
        # drive only the staleness logic with a hand-built puller
        peer = _FakePullPeer()
        from kungfu_tpu.optimizers.async_sgd import _ModelPuller

        p = _ModelPuller(peer, "m", 16, lambda: 1,
                         min_interval=30.0)  # one landing, then silence
        p.start()
        try:
            assert p.wait_landed(5.0)
            _, seq = p.take()
            # consume the same landing repeatedly; wait_landed on a silent
            # wire returns False after the bound, not hang
            import time

            t0 = time.monotonic()
            assert not p.wait_landed(0.3)
            assert time.monotonic() - t0 < 2.0
        finally:
            p.close()

    def test_async_step_latency_independent_of_wire(self):
        """The whole point: with a slow wire, async step wall time stays
        at compute scale (blocking would pay the wire every step)."""
        import time

        import optax

        class _FakeGossipPeer:
            """Just enough peer surface for the optimizer + puller."""

            def __init__(self, wire_s=0.3):
                self.wire_s = wire_s
                self.blobs = {}

            def rank(self):
                return 0

            def size(self):
                return 2

            def save(self, name, blob, version=None, copy=True):
                self.blobs[name] = np.asarray(blob).copy()

            def barrier(self):
                pass

            def request_into(self, target, name, buf, version=None,
                             timeout=None, send_retries=None):
                time.sleep(self.wire_s)
                buf[:] = 7.0
                return buf

        from kungfu_tpu.optimizers.async_sgd import (
            AsyncPairAveragingOptimizer,
        )

        peer = _FakeGossipPeer(wire_s=0.3)
        opt = AsyncPairAveragingOptimizer(optax.sgd(0.0), peer=peer,
                                          pull_timeout=5.0)
        params = {"w": jnp.zeros(1024, jnp.float32)}
        state = opt.init(params)
        grads = {"w": jnp.zeros(1024, jnp.float32)}
        # first step blocks for the first landing; time the next 5
        params, state = opt.step(params, grads, state)
        try:
            t0 = time.perf_counter()
            for _ in range(5):
                params, state = opt.step(params, grads, state)
            wall = time.perf_counter() - t0
            # blocking would cost >= 5 * 0.3s; async stays at compute
            # scale plus at most one staleness wait
            assert wall < 1.0, f"async steps paid the wire: {wall:.2f}s"
            assert opt.averaged_steps + opt.local_steps == 6
            # the averaged value actually came from the landed model:
            # step 1 averaged 0 with 7 -> 3.5
            assert float(np.asarray(params["w"])[0]) > 0.0
        finally:
            opt.close()

    def test_async_gossip_survives_peer_departure(self):
        """A peer closing mid-gossip must not kill the puller thread or
        the survivors' steps: pulls from the dead peer miss (timeout or
        connection error), the staleness bound keeps the step bounded,
        and averaging resumes between the survivors."""
        from kungfu_tpu.optimizers import AsyncPairAveragingOptimizer
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.store.store import reset_local_store
        from kungfu_tpu.utils.envs import Config

        reset_local_store()
        workers = PeerList.parse(
            "127.0.0.1:24021,127.0.0.1:24022,127.0.0.1:24023")
        cluster = Cluster(PeerList.parse("127.0.0.1:38083"), workers)
        peers = [Peer(Config(self_id=workers[i], cluster=cluster))
                 for i in range(3)]
        for p in peers:
            p.start()
        opts = []
        try:
            opts = [AsyncPairAveragingOptimizer(
                optax.sgd(0.0), peer=p, selector="roundrobin",
                pull_timeout=2.0, max_staleness=2) for p in peers]
            params = [{"w": jnp.full(4, float(i), jnp.float32)}
                      for i in range(3)]
            import threading

            states = [None] * 3

            def init_one(i):
                states[i] = opts[i].init(params[i])

            ts = [threading.Thread(target=init_one, args=(i,))
                  for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            grads = {"w": jnp.zeros(4, jnp.float32)}
            for i in range(3):
                params[i], states[i] = opts[i].step(params[i], grads,
                                                    states[i])
            # peer 2 leaves without ceremony
            opts[2].close()
            peers[2].close()
            avg_before = [opts[i].averaged_steps for i in range(2)]
            # survivors keep stepping; round-robin targets include the
            # dead peer — those pulls miss, the thread must survive
            import time

            t0 = time.monotonic()
            for _ in range(4):
                for i in range(2):
                    params[i], states[i] = opts[i].step(params[i], grads,
                                                        states[i])
            assert time.monotonic() - t0 < 60.0
            for i in range(2):
                assert opts[i]._puller.is_alive()
                # averaging CONTINUED after the departure (fresh landings
                # from the live peer, or reuse of the last landing) —
                # the pre-departure steps alone must not satisfy this
                assert opts[i].averaged_steps > avg_before[i]
        finally:
            for o in opts[:2]:
                o.close()
            for p in peers[:2]:
                p.close()
            reset_local_store()

    def test_bf16_wire_gossip(self):
        """fuse_dtype=bfloat16 halves gossip wire bytes; the whole
        store/serve/registered-receive chain must survive an ml_dtypes
        dtype that does not export the buffer protocol (the model
        travels as raw uint8 views)."""
        import threading

        from kungfu_tpu.optimizers import AsyncPairAveragingOptimizer
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.store.store import reset_local_store
        from kungfu_tpu.utils.envs import Config

        reset_local_store()
        workers = PeerList.parse("127.0.0.1:24031,127.0.0.1:24032")
        cluster = Cluster(PeerList.parse("127.0.0.1:38084"), workers)
        peers = [Peer(Config(self_id=workers[i], cluster=cluster))
                 for i in range(2)]
        for p in peers:
            p.start()
        opts = []
        try:
            opts = [AsyncPairAveragingOptimizer(
                optax.sgd(0.0), peer=p, selector="roundrobin",
                fuse_dtype=jnp.bfloat16) for p in peers]
            params = [{"w": jnp.zeros(64, jnp.float32)},
                      {"w": jnp.ones(64, jnp.float32) * 2.0}]
            states = [None, None]

            def init_one(i):
                states[i] = opts[i].init(params[i])

            ts = [threading.Thread(target=init_one, args=(i,))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            grads = {"w": jnp.zeros(64, jnp.float32)}
            p0, _ = opts[0].step(params[0], grads, states[0])
            np.testing.assert_allclose(
                np.asarray(p0["w"], np.float32), np.ones(64), rtol=1e-2)
            # 64 params x 2 bytes on the wire per landed model
            assert opts[0].pull_bytes % 128 == 0 and opts[0].pull_bytes > 0
        finally:
            for o in opts:
                o.close()
            for p in peers:
                p.close()
            reset_local_store()
