"""Shared test helpers."""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Sequence


def run_all(fns: Sequence[Callable], timeout: float = 120) -> List:
    """Run callables concurrently (one thread each), return their results
    in order.  Raises the first exception any of them raised, and raises
    ``TimeoutError`` if any is still running after ``timeout`` — a hung
    collective must fail the test loudly, not surface later as a
    mysterious ``None`` result.  Threads are daemons so a hang can't also
    wedge interpreter exit."""
    outs = [None] * len(fns)
    errs = []

    def wrap(i, f):
        try:
            outs[i] = f()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [
        threading.Thread(target=wrap, args=(i, f), daemon=True)
        for i, f in enumerate(fns)
    ]
    for t in ts:
        t.start()
    # one shared deadline, not timeout-per-join: a fully hung N-thread
    # cluster must fail after ~timeout, not N*timeout
    deadline = time.monotonic() + timeout
    for t in ts:
        t.join(max(0.0, deadline - time.monotonic()))
    if errs:
        raise errs[0]
    hung = [i for i, t in enumerate(ts) if t.is_alive()]
    if hung:
        raise TimeoutError(
            f"worker threads {hung} still running after {timeout}s "
            "(deadlocked collective?)"
        )
    return outs
