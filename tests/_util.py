"""Shared test helpers."""

from __future__ import annotations

import threading
from typing import Callable, List, Sequence


def run_all(fns: Sequence[Callable], timeout: float = 120) -> List:
    """Run callables concurrently (one thread each), return their results
    in order.  Raises the first exception any of them raised, and raises
    ``TimeoutError`` if any is still running after ``timeout`` — a hung
    collective must fail the test loudly, not surface later as a
    mysterious ``None`` result.  Threads are daemons so a hang can't also
    wedge interpreter exit."""
    outs = [None] * len(fns)
    errs = []

    def wrap(i, f):
        try:
            outs[i] = f()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [
        threading.Thread(target=wrap, args=(i, f), daemon=True)
        for i, f in enumerate(fns)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    if errs:
        raise errs[0]
    hung = [i for i, t in enumerate(ts) if t.is_alive()]
    if hung:
        raise TimeoutError(
            f"worker threads {hung} still running after {timeout}s "
            "(deadlocked collective?)"
        )
    return outs
