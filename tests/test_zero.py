"""Weight-update sharding (ZeRO-1): exactness vs plain S-SGD, per-device
optimizer-state memory, padding, hierarchical meshes.

The technique (reduce-scatter grads → shard update → all-gather params)
is exactly equivalent to the replicated update for elementwise inner
transforms — these tests pin that equivalence against
``dp_train_step + synchronous_sgd`` on the 8-device virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.parallel.train import dp_train_step
from kungfu_tpu.parallel.zero import opt_state_bytes, zero1_train_step
from kungfu_tpu.optimizers import synchronous_sgd

N_DEV = 8


def _params(sizes=((13, 7), (7,), (7, 5))):
    rng = np.random.RandomState(0)
    return {
        f"w{i}": jnp.asarray(rng.randn(*s), jnp.float32)
        for i, s in enumerate(sizes)
    }


def _loss_fn(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w0"] + params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def _batch(n=16):
    rng = np.random.RandomState(1)
    return (jnp.asarray(rng.randn(n, 13), jnp.float32),
            jnp.asarray(rng.randn(n, 5), jnp.float32))


def _reference_step(comm, inner, params, batch):
    tx = synchronous_sgd(inner, comm.axis)
    step = dp_train_step(_loss_fn, tx, comm)
    p1, _, loss = step(params, tx.init(params), batch)
    return p1, loss


class TestZero1:
    @pytest.mark.parametrize("local_size", [8, 4])
    @pytest.mark.parametrize("make_inner", [
        lambda: optax.sgd(0.1, momentum=0.9),
        lambda: optax.adam(1e-2),
        lambda: optax.adamw(1e-2, weight_decay=0.01),
    ], ids=["momentum", "adam", "adamw"])
    def test_matches_replicated_update(self, local_size, make_inner):
        comm = Communicator(devices=jax.devices()[:N_DEV],
                            local_size=local_size)
        params, batch = _params(), _batch()
        ref_p, ref_loss = _reference_step(comm, make_inner(), params, batch)

        step, init_opt = zero1_train_step(_loss_fn, make_inner(), comm)
        opt = init_opt(params)
        p1, opt1, loss = step(params, opt, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(ref_p[k]),
                rtol=1e-5, atol=1e-6, err_msg=k)

    def test_opt_state_is_sharded(self):
        """Each device holds 1/n of the momentum (plus padding) — the
        entire point of the technique."""
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params, batch = _params(), _batch()
        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.1, momentum=0.9), comm)
        opt = init_opt(params)
        total = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(params))
        mom = [l for l in jax.tree_util.tree_leaves(opt)
               if hasattr(l, "shape") and l.ndim == 1]
        assert mom, opt
        chunk = -(-total // N_DEV)  # ceil
        for leaf in mom:
            assert leaf.shape[0] == chunk * N_DEV  # padded global
            shard_sizes = {
                int(np.prod(s.data.shape)) for s in leaf.addressable_shards
            }
            assert shard_sizes == {chunk}, shard_sizes
        # global optimizer footprint ~= one full momentum (split across
        # devices), NOT n replicated copies
        full_tx = optax.sgd(0.1, momentum=0.9)
        full_bytes = opt_state_bytes(full_tx.init(params))
        assert opt_state_bytes(opt) <= full_bytes + chunk * N_DEV * 4

    def test_multiple_steps_track_reference(self):
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params, batch = _params(), _batch()
        inner = optax.sgd(0.05, momentum=0.9)
        tx = synchronous_sgd(inner, comm.axis)
        ref_step = dp_train_step(_loss_fn, tx, comm)
        ref_p, ref_o = params, tx.init(params)

        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.05, momentum=0.9), comm)
        p, o = params, init_opt(params)
        for _ in range(3):
            ref_p, ref_o, _ = ref_step(ref_p, ref_o, batch)
            p, o, _ = step(p, o, batch)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p[k]), np.asarray(ref_p[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_reshard_across_resize_tracks_reference(self):
        """An elastic resize mid-run (8 → 4 devices) with zero1_reshard
        must continue EXACTLY like the replicated optimizer seeing the
        same global batches: momentum state survives the re-chunking."""
        from kungfu_tpu.parallel.zero import zero1_reshard

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch(16)  # 16 divides 8 and 4
        inner = lambda: optax.adam(1e-2)  # noqa: E731 — two-moment state

        # reference: replicated S-SGD over the SAME global batches, mesh
        # change irrelevant to its math
        tx = synchronous_sgd(inner(), c8.axis)
        ref_step8 = dp_train_step(_loss_fn, tx, c8)
        tx4 = synchronous_sgd(inner(), c4.axis)
        ref_step4 = dp_train_step(_loss_fn, tx4, c4)
        ref_p, ref_o = params, tx.init(params)
        for _ in range(2):
            ref_p, ref_o, _ = ref_step8(ref_p, ref_o, batch)
        # carry the OPTIMIZER state across the mesh change (replicated
        # state has no geometry — only its placement moves epochs)
        from kungfu_tpu.initializer import resync_parameters

        ref_p = resync_parameters(ref_p, comm=c4)
        ref_o = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), c4.replicated_sharding()),
            ref_o)
        for _ in range(2):
            ref_p, ref_o, _ = ref_step4(ref_p, ref_o, batch)

        step8, init8 = zero1_train_step(_loss_fn, inner(), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)
        o = zero1_reshard(o, p, c4)
        p = resync_parameters(p, comm=c4)  # params re-place replicated
        step4, _ = zero1_train_step(_loss_fn, inner(), c4)
        for _ in range(2):
            p, o, _ = step4(p, o, batch)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p[k]), np.asarray(ref_p[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_snapshot_restore_roundtrip_across_resize(self):
        """snapshot → restore across 8→4 must agree exactly with
        zero1_reshard (the host-plane path for provisioned worlds, here
        exercised channel-less: every chunk is locally addressable)."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_restore,
                                              zero1_snapshot)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch()
        step8, init8 = zero1_train_step(_loss_fn, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)

        blob = zero1_snapshot(o)
        want = zero1_reshard(o, p, c4)
        _, init4 = zero1_train_step(_loss_fn, optax.adam(1e-2), c4)
        got = zero1_restore(blob, init4(p), p, new_comm=c4)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_detects_missing_chunks(self):
        """A snapshot missing a contributor's chunks must raise, not
        silently restore zeros into the momentum."""
        import io

        from kungfu_tpu.parallel.zero import zero1_restore, zero1_snapshot

        comm = Communicator(devices=jax.devices()[:8], local_size=8)
        params = _params()
        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.1, momentum=0.9), comm)
        o = init_opt(params)
        blob = zero1_snapshot(o)
        with np.load(io.BytesIO(blob)) as z:
            kept = {k: z[k] for k in z.files if not k.endswith("_o0")}
        bio = io.BytesIO()
        np.savez(bio, **kept)
        with pytest.raises(ValueError, match="missing"):
            zero1_restore(bio.getvalue(), init_opt(params), params,
                          new_comm=comm)

    def test_reshard_multicontroller_routes_to_host_plane(self):
        """A multi-controller mesh routes reshard through the
        snapshot/restore host plane (one entry point); without the
        snapshot the contract violation is loud, not a silent
        mis-shard."""
        from kungfu_tpu.parallel.zero import zero1_reshard, zero1_snapshot

        comm = Communicator(devices=jax.devices()[:4], local_size=4)
        _, init_opt = zero1_train_step(_loss_fn, optax.sgd(0.1), comm)
        o = init_opt(_params())
        comm._multiproc = True  # simulate a provisioned-world mesh
        with pytest.raises(ValueError, match="snapshot"):
            zero1_reshard(o, _params(), comm)
        # with the pre-resize snapshot the fold works even on the
        # simulated multi-controller flag (all chunks addressable here)
        blob = zero1_snapshot(o)
        comm._multiproc = False  # placement back on the real local mesh
        got = zero1_reshard(o, _params(), comm, snapshot=blob)
        for a, b in zip(jax.tree_util.tree_leaves(o),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_odd_total_size_pads(self):
        """A parameter count not divisible by n exercises the pad path
        end to end (pad grads are zero, pad params stay zero)."""
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params = {"w": jnp.asarray(np.random.RandomState(3).randn(3, 5),
                                   jnp.float32)}  # 15 elements, n=8

        def loss(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        rng = np.random.RandomState(4)
        batch = (jnp.asarray(rng.randn(16, 3), jnp.float32),
                 jnp.asarray(rng.randn(16, 5), jnp.float32))
        tx = synchronous_sgd(optax.sgd(0.1), comm.axis)
        ref_p, _, _ = dp_train_step(loss, tx, comm)(
            params, tx.init(params), batch)

        step, init_opt = zero1_train_step(loss, optax.sgd(0.1), comm)
        p1, _, _ = step(params, init_opt(params), batch)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(ref_p["w"]),
                                   rtol=1e-5, atol=1e-6)


class TestReshardSnapshotFold:
    def test_reshard_with_snapshot_matches_direct(self):
        """zero1_reshard(snapshot=...) — the folded host-plane path — is
        value-identical to the direct single-controller re-placement,
        with structure supplied by a FRESH init (the joiner contract)."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_snapshot,
                                              zero1_train_step)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch()
        step8, init8 = zero1_train_step(_loss_fn, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)

        want = zero1_reshard(o, p, c4)
        blob = zero1_snapshot(o)
        _, init4 = zero1_train_step(_loss_fn, optax.adam(1e-2), c4)
        got = zero1_reshard(init4(p), p, c4, snapshot=blob)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the sharded placement really is 1/n on the new mesh
        vec = [l for l in jax.tree_util.tree_leaves(got)
               if getattr(l, "ndim", 0) == 1]
        assert vec and all(
            len(l.sharding.device_set) == 4 for l in vec)


# ==========================================================================
# ZeRO-2 / ZeRO-3 (zero_train_step) — bucketed reduce-scatter, sharded
# params, measured comm volume
# ==========================================================================


def _comm8(version=0):
    return Communicator(devices=jax.devices()[:8], local_size=8,
                        version=version)


class TestZeroStages:
    """Staged steps must reproduce the replicated update exactly — the
    stage only changes WHERE bytes move, never the math."""

    @pytest.mark.parametrize("stage", [2, 3])
    @pytest.mark.parametrize("make_inner", [
        lambda: optax.sgd(0.1, momentum=0.9),
        lambda: optax.adam(1e-2),
    ], ids=["momentum", "adam"])
    def test_matches_replicated_update(self, stage, make_inner):
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params, batch = _params(), _batch()
        ref_p, ref_loss = _reference_step(comm, make_inner(), params, batch)
        z = zero_train_step(_loss_fn, make_inner(), comm, stage=stage)
        o = z.init_opt(params)
        p = z.init_params(params)
        p, o, loss = z.step(p, o, batch)
        full = z.gather_params(p)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(full[k]), np.asarray(ref_p[k]),
                rtol=1e-5, atol=1e-6, err_msg=k)

    def test_stage2_bitwise_vs_replicated_sgd(self):
        """With a stateless elementwise inner (plain SGD) the
        reduce-scatter path is BITWISE identical to the replicated
        all-reduce step on identical inputs — the psum and psum_scatter
        reductions see the same addends in the same combining order."""
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params, batch = _params(), _batch()
        ref_p, _ = _reference_step(comm, optax.sgd(0.1), params, batch)
        step, init_opt = zero_train_step(_loss_fn, optax.sgd(0.1), comm,
                                         stage=2)
        p, o, _ = step(params, init_opt(params), batch)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p[k]), np.asarray(ref_p[k]), err_msg=k)

    @pytest.mark.parametrize("stage", [2, 3])
    def test_bucketed_matches_unbucketed_bitwise(self, stage):
        """Folding the collective into many small buckets is pure
        program structure: the result must be bit-identical to the
        single-bucket step (the invariant that keeps the elastic state
        geometry stage- and bucket-agnostic)."""
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params, batch = _params(), _batch()
        runs = []
        for bb in (4 << 20, 16):  # one bucket vs ~width-4 buckets
            z = zero_train_step(_loss_fn, optax.adam(1e-2), comm,
                                stage=stage, bucket_bytes=bb)
            o = z.init_opt(params)
            p = z.init_params(params)
            p, o, _ = z.step(p, o, batch)
            runs.append(z.gather_params(p))
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(runs[0][k]), np.asarray(runs[1][k]), err_msg=k)

    def test_stage3_params_sharded_between_steps(self):
        """Stage 3's whole point: at rest each device holds 1/n of the
        flat parameter buffer; gather_params reassembles bitwise."""
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params = _params()
        z = zero_train_step(_loss_fn, optax.adam(1e-2), comm, stage=3)
        z.init_opt(params)
        p_shard = z.init_params(params)
        total = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(params))
        chunk = -(-total // N_DEV)
        assert p_shard.shape == (chunk * N_DEV,)
        assert {int(np.prod(s.data.shape))
                for s in p_shard.addressable_shards} == {chunk}
        back = z.gather_params(p_shard)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(back[k]), np.asarray(params[k]), err_msg=k)

    def test_stage3_multiple_steps_track_reference(self):
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params, batch = _params(), _batch()
        inner = lambda: optax.sgd(0.05, momentum=0.9)  # noqa: E731
        tx = synchronous_sgd(inner(), comm.axis)
        ref_step = dp_train_step(_loss_fn, tx, comm)
        ref_p, ref_o = params, tx.init(params)
        z = zero_train_step(_loss_fn, inner(), comm, stage=3)
        o = z.init_opt(params)
        p = z.init_params(params)
        for _ in range(3):
            ref_p, ref_o, _ = ref_step(ref_p, ref_o, batch)
            p, o, _ = z.step(p, o, batch)
        full = z.gather_params(p)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(full[k]), np.asarray(ref_p[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_unpacks_like_zero1(self):
        """step, init_opt = zero_train_step(...) keeps the ZeRO-1
        calling convention for stages 1/2."""
        from kungfu_tpu.parallel.zero import ZeroStep, zero_train_step

        comm = _comm8()
        params, batch = _params(), _batch()
        out = zero_train_step(_loss_fn, optax.sgd(0.1), comm, stage=2)
        assert isinstance(out, ZeroStep)
        step, init_opt = out
        p, o, loss = step(params, init_opt(params), batch)
        assert np.isfinite(float(loss))

    def test_invalid_stage_rejected(self):
        from kungfu_tpu.parallel.zero import zero_train_step

        with pytest.raises(ValueError, match="stage"):
            zero_train_step(_loss_fn, optax.sgd(0.1), _comm8(), stage=4)

    def test_stage3_step_before_init_params_raises(self):
        from kungfu_tpu.parallel.zero import zero_train_step

        z = zero_train_step(_loss_fn, optax.sgd(0.1), _comm8(), stage=3)
        params, batch = _params(), _batch()
        with pytest.raises(RuntimeError, match="init_params"):
            z.step(params, z.init_opt(params), batch)

    def test_one_rank_world_degenerate_shard(self):
        """n=1: chunk == total, no collective — every stage must still
        run (the regression the elastic re-shard generalization needs:
        a 1-rank world is a legal carve)."""
        from kungfu_tpu.parallel.zero import zero_train_step

        c1 = Communicator(devices=jax.devices()[:1], local_size=1)
        params, batch = _params(), _batch()
        want = None
        for stage in (1, 2, 3):
            z = zero_train_step(_loss_fn, optax.sgd(0.1), c1, stage=stage)
            o = z.init_opt(params)
            p = z.init_params(params)
            p, o, _ = z.step(p, o, batch)
            full = z.gather_params(p)
            if want is None:
                want = full
            else:
                for k in params:
                    np.testing.assert_array_equal(
                        np.asarray(full[k]), np.asarray(want[k]), err_msg=k)

    def test_dp_train_step_routes_zero_stage(self):
        from kungfu_tpu.parallel.zero import ZeroStep

        comm = _comm8()
        params, batch = _params(), _batch()
        out = dp_train_step(_loss_fn, optax.sgd(0.1), comm, zero_stage=2)
        assert isinstance(out, ZeroStep)
        step, init_opt = out
        p, o, loss = step(params, init_opt(params), batch)
        assert np.isfinite(float(loss))
        with pytest.raises(ValueError, match="zero_stage"):
            dp_train_step(_loss_fn, optax.sgd(0.1), comm, zero_stage=2,
                          has_aux=True)


class TestZeroCommVolume:
    """The measured perf claim: ZeRO-2's gradient collective moves at
    most ~55% of the ZeRO-1 all-reduce bytes (ring convention), read
    from the TRACED program, not from the formula that motivated it."""

    def _traced(self, stage, comm, params, batch):
        from kungfu_tpu.ops.schedules import traced_collective_bytes
        from kungfu_tpu.parallel.zero import zero_train_step

        z = zero_train_step(_loss_fn, optax.adam(1e-2), comm, stage=stage)
        o = z.init_opt(params)
        p = z.init_params(params)
        ax = dict(zip(comm.mesh.axis_names, comm.mesh.devices.shape))
        return traced_collective_bytes(
            lambda p_, o_, b_: z.step(p_, o_, b_), p, o, batch,
            axis_sizes=ax)

    def test_zero2_grad_bytes_at_most_55pct_of_zero1(self):
        comm = _comm8()
        params, batch = _params(), _batch()
        m1 = self._traced(1, comm, params, batch)
        m2 = self._traced(2, comm, params, batch)
        # stage 1's gradient path is a psum (all-reduce); stage 2's is a
        # reduce_scatter.  The loss pmean rides both (few bytes).
        assert "psum" in m1 and "reduce_scatter" not in m1, m1
        assert "reduce_scatter" in m2, m2
        ratio = sum(m2.values()) / sum(m1.values())
        assert ratio <= 0.55, (ratio, m1, m2)

    def test_zero3_gathers_params_in_step(self):
        comm = _comm8()
        params, batch = _params(), _batch()
        m3 = self._traced(3, comm, params, batch)
        # JIT parameter all-gather + its reduce-scatter transpose both
        # live INSIDE the traced step at stage 3
        assert "all_gather" in m3 and "reduce_scatter" in m3, m3

    def test_analytic_table(self):
        from kungfu_tpu.parallel.zero import zero_comm_bytes

        b1 = zero_comm_bytes(1000, 8, 1)
        b2 = zero_comm_bytes(1000, 8, 2)
        b3 = zero_comm_bytes(1000, 8, 3)
        assert b1["grad_bytes"] == 2 * b2["grad_bytes"]
        assert b2 == b3  # stage 3 moves the same bytes, placed JIT
        assert b1["param_bytes"] == b2["param_bytes"]
        with pytest.raises(ValueError):
            zero_comm_bytes(1000, 0, 2)

    def test_zerostep_comm_bytes_accessor(self):
        from kungfu_tpu.parallel.zero import zero_train_step

        comm = _comm8()
        params = _params()
        z = zero_train_step(_loss_fn, optax.adam(1e-2), comm, stage=2)
        cb = z.comm_bytes(params)
        assert set(cb) >= {"grad_bytes", "param_bytes", "total_bytes"}
        assert cb["grad_bytes"] == cb["param_bytes"]  # both (n-1)/n * N


class TestReshardEdgeCases:
    """The zero1_reshard generalization prerequisites: worlds where the
    padded total shrinks below an old rank's shard offset, and 1-rank
    (degenerate) worlds on either side."""

    def _trained(self, comm, params, batch, steps=1):
        step, init_opt = zero1_train_step(_loss_fn, optax.adam(1e-2), comm)
        p, o = params, init_opt(params)
        for _ in range(steps):
            p, o, _ = step(p, o, batch)
        return p, o

    def test_padded_total_shrinks_below_old_shard(self):
        """total=15 over 8 ranks pads to 16 (rank 7 owns [14:16)); the
        5-rank world pads to 15 < 16 — the old top shard's padding must
        vanish, not shift values."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_restore,
                                              zero1_snapshot)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c5 = Communicator(devices=devs[:5], local_size=5, version=1)
        params = {"w": jnp.asarray(np.random.RandomState(3).randn(3, 5),
                                   jnp.float32)}

        def loss(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        rng = np.random.RandomState(4)
        batch = (jnp.asarray(rng.randn(16, 3), jnp.float32),
                 jnp.asarray(rng.randn(16, 5), jnp.float32))
        step8, init8 = zero1_train_step(loss, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        p, o, _ = step8(p, o, batch)

        o5 = zero1_reshard(o, p, c5)
        for a, b in zip(jax.tree_util.tree_leaves(o),
                        jax.tree_util.tree_leaves(o5)):
            a, b = np.asarray(a), np.asarray(b)
            if a.ndim:
                assert b.shape == (15,)
                np.testing.assert_array_equal(a[:15], b)
            else:
                np.testing.assert_array_equal(a, b)
        # snapshot/restore agrees with the direct re-placement
        blob = zero1_snapshot(o)
        _, init5 = zero1_train_step(loss, optax.adam(1e-2), c5)
        got = zero1_restore(blob, init5(p), p, new_comm=c5)
        for a, b in zip(jax.tree_util.tree_leaves(o5),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_one_rank_world_roundtrip(self):
        """8 -> 1 -> 8: the 1-rank world is a legal degenerate carve
        (chunk == total, no padding); values round-trip bitwise."""
        from kungfu_tpu.parallel.zero import zero1_reshard

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c1 = Communicator(devices=devs[:1], local_size=1, version=1)
        params, batch = _params(), _batch()
        p, o = self._trained(c8, params, batch)
        o1 = zero1_reshard(o, p, c1)
        total = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(params))
        for l in jax.tree_util.tree_leaves(o1):
            if getattr(l, "ndim", 0):
                assert l.shape == (total,)  # no padding at n=1
        c8b = Communicator(devices=devs[:8], local_size=8, version=2)
        o8 = zero1_reshard(o1, p, c8b)
        for a, b in zip(jax.tree_util.tree_leaves(o),
                        jax.tree_util.tree_leaves(o8)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_old_world_larger_than_param_count(self):
        """total=5 over 8 ranks: ranks 5..7 hold PURE padding — their
        chunks must neither break the snapshot tiling check nor leak
        padding into the 3-rank re-carve."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_restore,
                                              zero1_snapshot)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c3 = Communicator(devices=devs[:3], local_size=3, version=1)
        params = {"w": jnp.asarray(np.random.RandomState(5).randn(5),
                                   jnp.float32)}
        _, init8 = zero1_train_step(
            lambda p, b: jnp.sum(p["w"] ** 2), optax.adam(1e-2), c8)
        o = init8(params)
        o3 = zero1_reshard(o, params, c3)
        blob = zero1_snapshot(o)
        _, init3 = zero1_train_step(
            lambda p, b: jnp.sum(p["w"] ** 2), optax.adam(1e-2), c3)
        got = zero1_restore(blob, init3(params), params, new_comm=c3)
        for a, b in zip(jax.tree_util.tree_leaves(o3),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestReshardPlan:
    @pytest.mark.parametrize("total,old_n,new_n", [
        (10, 4, 1), (10, 1, 4), (7, 3, 5), (100, 4, 2), (5, 8, 3),
        (16, 4, 4), (1, 1, 1), (3, 8, 8),
    ])
    def test_plan_partitions_exactly(self, total, old_n, new_n):
        """Segments tile [0, total) with no gap or overlap, and every
        segment lies inside BOTH its old and its new owner's chunk."""
        from kungfu_tpu.parallel.zero import reshard_plan

        plan = reshard_plan(total, old_n, new_n)
        oc, nc = -(-total // old_n), -(-total // new_n)
        cover = np.zeros(total, bool)
        for (o, r, s, ln) in plan:
            assert ln > 0
            assert not cover[s:s + ln].any(), "overlap"
            cover[s:s + ln] = True
            assert o * oc <= s and s + ln <= min((o + 1) * oc, total)
            assert r * nc <= s and s + ln <= min((r + 1) * nc, total)
        assert cover.all(), "gap"

    def test_identity_world_is_identity(self):
        from kungfu_tpu.parallel.zero import reshard_plan

        for (o, r, s, ln) in reshard_plan(64, 4, 4):
            assert o == r

    def test_invalid_world_sizes(self):
        from kungfu_tpu.parallel.zero import reshard_plan

        with pytest.raises(ValueError):
            reshard_plan(10, 0, 2)
        with pytest.raises(ValueError):
            reshard_plan(10, 2, 0)


class TestZeroReshardP2P:
    def test_single_controller_matches_zero1_reshard(self):
        """The leaderless segment-exchange re-carve (numpy replay of the
        wire plan) is bitwise identical to the direct re-placement."""
        from kungfu_tpu.parallel.zero import zero1_reshard, zero_reshard_p2p

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch()
        step8, init8 = zero1_train_step(_loss_fn, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)
        want = zero1_reshard(o, p, c4)
        got = zero_reshard_p2p(o, p, c4)  # old_n inferred from sharding
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grow_matches_direct(self):
        from kungfu_tpu.parallel.zero import zero1_reshard, zero_reshard_p2p

        devs = jax.devices()
        c4 = Communicator(devices=devs[:4], local_size=4, version=0)
        c8 = Communicator(devices=devs[:8], local_size=8, version=1)
        params, batch = _params(), _batch()
        step4, init4 = zero1_train_step(_loss_fn, optax.adam(1e-2), c4)
        p, o = params, init4(params)
        p, o, _ = step4(p, o, batch)
        want = zero1_reshard(o, p, c8)
        got = zero_reshard_p2p(o, p, c8, old_n=4)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestOptStateGauge:
    def test_record_opt_state_gauge(self):
        """init_opt publishes the per-rank footprint as the
        kf_opt_state_bytes gauge (the kftop / /metrics memory column)."""
        from kungfu_tpu.monitor.registry import REGISTRY
        from kungfu_tpu.parallel.zero import (opt_state_bytes_per_device,
                                              zero_train_step)

        comm = _comm8()
        params = _params()
        z = zero_train_step(_loss_fn, optax.adam(1e-2), comm, stage=2)
        o = z.init_opt(params)
        want = opt_state_bytes_per_device(o)
        assert want > 0
        assert REGISTRY.gauge("kf_opt_state_bytes").value == want
