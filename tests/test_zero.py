"""Weight-update sharding (ZeRO-1): exactness vs plain S-SGD, per-device
optimizer-state memory, padding, hierarchical meshes.

The technique (reduce-scatter grads → shard update → all-gather params)
is exactly equivalent to the replicated update for elementwise inner
transforms — these tests pin that equivalence against
``dp_train_step + synchronous_sgd`` on the 8-device virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.parallel.train import dp_train_step
from kungfu_tpu.parallel.zero import opt_state_bytes, zero1_train_step
from kungfu_tpu.optimizers import synchronous_sgd

N_DEV = 8


def _params(sizes=((13, 7), (7,), (7, 5))):
    rng = np.random.RandomState(0)
    return {
        f"w{i}": jnp.asarray(rng.randn(*s), jnp.float32)
        for i, s in enumerate(sizes)
    }


def _loss_fn(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["w0"] + params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def _batch(n=16):
    rng = np.random.RandomState(1)
    return (jnp.asarray(rng.randn(n, 13), jnp.float32),
            jnp.asarray(rng.randn(n, 5), jnp.float32))


def _reference_step(comm, inner, params, batch):
    tx = synchronous_sgd(inner, comm.axis)
    step = dp_train_step(_loss_fn, tx, comm)
    p1, _, loss = step(params, tx.init(params), batch)
    return p1, loss


class TestZero1:
    @pytest.mark.parametrize("local_size", [8, 4])
    @pytest.mark.parametrize("make_inner", [
        lambda: optax.sgd(0.1, momentum=0.9),
        lambda: optax.adam(1e-2),
        lambda: optax.adamw(1e-2, weight_decay=0.01),
    ], ids=["momentum", "adam", "adamw"])
    def test_matches_replicated_update(self, local_size, make_inner):
        comm = Communicator(devices=jax.devices()[:N_DEV],
                            local_size=local_size)
        params, batch = _params(), _batch()
        ref_p, ref_loss = _reference_step(comm, make_inner(), params, batch)

        step, init_opt = zero1_train_step(_loss_fn, make_inner(), comm)
        opt = init_opt(params)
        p1, opt1, loss = step(params, opt, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(ref_p[k]),
                rtol=1e-5, atol=1e-6, err_msg=k)

    def test_opt_state_is_sharded(self):
        """Each device holds 1/n of the momentum (plus padding) — the
        entire point of the technique."""
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params, batch = _params(), _batch()
        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.1, momentum=0.9), comm)
        opt = init_opt(params)
        total = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(params))
        mom = [l for l in jax.tree_util.tree_leaves(opt)
               if hasattr(l, "shape") and l.ndim == 1]
        assert mom, opt
        chunk = -(-total // N_DEV)  # ceil
        for leaf in mom:
            assert leaf.shape[0] == chunk * N_DEV  # padded global
            shard_sizes = {
                int(np.prod(s.data.shape)) for s in leaf.addressable_shards
            }
            assert shard_sizes == {chunk}, shard_sizes
        # global optimizer footprint ~= one full momentum (split across
        # devices), NOT n replicated copies
        full_tx = optax.sgd(0.1, momentum=0.9)
        full_bytes = opt_state_bytes(full_tx.init(params))
        assert opt_state_bytes(opt) <= full_bytes + chunk * N_DEV * 4

    def test_multiple_steps_track_reference(self):
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params, batch = _params(), _batch()
        inner = optax.sgd(0.05, momentum=0.9)
        tx = synchronous_sgd(inner, comm.axis)
        ref_step = dp_train_step(_loss_fn, tx, comm)
        ref_p, ref_o = params, tx.init(params)

        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.05, momentum=0.9), comm)
        p, o = params, init_opt(params)
        for _ in range(3):
            ref_p, ref_o, _ = ref_step(ref_p, ref_o, batch)
            p, o, _ = step(p, o, batch)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p[k]), np.asarray(ref_p[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_reshard_across_resize_tracks_reference(self):
        """An elastic resize mid-run (8 → 4 devices) with zero1_reshard
        must continue EXACTLY like the replicated optimizer seeing the
        same global batches: momentum state survives the re-chunking."""
        from kungfu_tpu.parallel.zero import zero1_reshard

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch(16)  # 16 divides 8 and 4
        inner = lambda: optax.adam(1e-2)  # noqa: E731 — two-moment state

        # reference: replicated S-SGD over the SAME global batches, mesh
        # change irrelevant to its math
        tx = synchronous_sgd(inner(), c8.axis)
        ref_step8 = dp_train_step(_loss_fn, tx, c8)
        tx4 = synchronous_sgd(inner(), c4.axis)
        ref_step4 = dp_train_step(_loss_fn, tx4, c4)
        ref_p, ref_o = params, tx.init(params)
        for _ in range(2):
            ref_p, ref_o, _ = ref_step8(ref_p, ref_o, batch)
        # carry the OPTIMIZER state across the mesh change (replicated
        # state has no geometry — only its placement moves epochs)
        from kungfu_tpu.initializer import resync_parameters

        ref_p = resync_parameters(ref_p, comm=c4)
        ref_o = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), c4.replicated_sharding()),
            ref_o)
        for _ in range(2):
            ref_p, ref_o, _ = ref_step4(ref_p, ref_o, batch)

        step8, init8 = zero1_train_step(_loss_fn, inner(), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)
        o = zero1_reshard(o, p, c4)
        p = resync_parameters(p, comm=c4)  # params re-place replicated
        step4, _ = zero1_train_step(_loss_fn, inner(), c4)
        for _ in range(2):
            p, o, _ = step4(p, o, batch)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p[k]), np.asarray(ref_p[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)

    def test_snapshot_restore_roundtrip_across_resize(self):
        """snapshot → restore across 8→4 must agree exactly with
        zero1_reshard (the host-plane path for provisioned worlds, here
        exercised channel-less: every chunk is locally addressable)."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_restore,
                                              zero1_snapshot)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch()
        step8, init8 = zero1_train_step(_loss_fn, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)

        blob = zero1_snapshot(o)
        want = zero1_reshard(o, p, c4)
        _, init4 = zero1_train_step(_loss_fn, optax.adam(1e-2), c4)
        got = zero1_restore(blob, init4(p), p, new_comm=c4)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_detects_missing_chunks(self):
        """A snapshot missing a contributor's chunks must raise, not
        silently restore zeros into the momentum."""
        import io

        from kungfu_tpu.parallel.zero import zero1_restore, zero1_snapshot

        comm = Communicator(devices=jax.devices()[:8], local_size=8)
        params = _params()
        step, init_opt = zero1_train_step(
            _loss_fn, optax.sgd(0.1, momentum=0.9), comm)
        o = init_opt(params)
        blob = zero1_snapshot(o)
        with np.load(io.BytesIO(blob)) as z:
            kept = {k: z[k] for k in z.files if not k.endswith("_o0")}
        bio = io.BytesIO()
        np.savez(bio, **kept)
        with pytest.raises(ValueError, match="missing"):
            zero1_restore(bio.getvalue(), init_opt(params), params,
                          new_comm=comm)

    def test_reshard_multicontroller_routes_to_host_plane(self):
        """A multi-controller mesh routes reshard through the
        snapshot/restore host plane (one entry point); without the
        snapshot the contract violation is loud, not a silent
        mis-shard."""
        from kungfu_tpu.parallel.zero import zero1_reshard, zero1_snapshot

        comm = Communicator(devices=jax.devices()[:4], local_size=4)
        _, init_opt = zero1_train_step(_loss_fn, optax.sgd(0.1), comm)
        o = init_opt(_params())
        comm._multiproc = True  # simulate a provisioned-world mesh
        with pytest.raises(ValueError, match="snapshot"):
            zero1_reshard(o, _params(), comm)
        # with the pre-resize snapshot the fold works even on the
        # simulated multi-controller flag (all chunks addressable here)
        blob = zero1_snapshot(o)
        comm._multiproc = False  # placement back on the real local mesh
        got = zero1_reshard(o, _params(), comm, snapshot=blob)
        for a, b in zip(jax.tree_util.tree_leaves(o),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_odd_total_size_pads(self):
        """A parameter count not divisible by n exercises the pad path
        end to end (pad grads are zero, pad params stay zero)."""
        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8)
        params = {"w": jnp.asarray(np.random.RandomState(3).randn(3, 5),
                                   jnp.float32)}  # 15 elements, n=8

        def loss(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        rng = np.random.RandomState(4)
        batch = (jnp.asarray(rng.randn(16, 3), jnp.float32),
                 jnp.asarray(rng.randn(16, 5), jnp.float32))
        tx = synchronous_sgd(optax.sgd(0.1), comm.axis)
        ref_p, _, _ = dp_train_step(loss, tx, comm)(
            params, tx.init(params), batch)

        step, init_opt = zero1_train_step(loss, optax.sgd(0.1), comm)
        p1, _, _ = step(params, init_opt(params), batch)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(ref_p["w"]),
                                   rtol=1e-5, atol=1e-6)


class TestReshardSnapshotFold:
    def test_reshard_with_snapshot_matches_direct(self):
        """zero1_reshard(snapshot=...) — the folded host-plane path — is
        value-identical to the direct single-controller re-placement,
        with structure supplied by a FRESH init (the joiner contract)."""
        from kungfu_tpu.parallel.zero import (zero1_reshard, zero1_snapshot,
                                              zero1_train_step)

        devs = jax.devices()
        c8 = Communicator(devices=devs[:8], local_size=8, version=0)
        c4 = Communicator(devices=devs[:4], local_size=4, version=1)
        params, batch = _params(), _batch()
        step8, init8 = zero1_train_step(_loss_fn, optax.adam(1e-2), c8)
        p, o = params, init8(params)
        for _ in range(2):
            p, o, _ = step8(p, o, batch)

        want = zero1_reshard(o, p, c4)
        blob = zero1_snapshot(o)
        _, init4 = zero1_train_step(_loss_fn, optax.adam(1e-2), c4)
        got = zero1_reshard(init4(p), p, c4, snapshot=blob)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the sharded placement really is 1/n on the new mesh
        vec = [l for l in jax.tree_util.tree_leaves(got)
               if getattr(l, "ndim", 0) == 1]
        assert vec and all(
            len(l.sharding.device_set) == 4 for l in vec)
