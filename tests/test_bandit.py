"""kf-adapt: the UCB collective bandit (ISSUE 9).

Covers the satellite test checklist end to end:

* deterministic-seed arm convergence on synthetic latency streams
  (identical replicas make identical selection sequences);
* the size-bucketed schedule table: independent winners per bucket,
  installed into the device communicator's per-``nbytes`` dispatch;
* consensus-fenced swap identical on every rank (3-rank in-process
  cluster) with the ``swap`` timeline event on each rank at one seq;
* bandit state reset/re-explore across a LIVE resize (``elastic_step``'s
  ``bandit=`` wiring, 3 -> 2 through the real config-server protocol);
* a chaos-``delay`` run where the policy abandons the degraded strategy;
* the load-scaled host pool and the hardened autotune winner guard.
"""

from __future__ import annotations

import random
import threading
import time

import numpy as np
import pytest

from tests._util import run_all


class TestArmStats:
    def test_deterministic_convergence_on_synthetic_stream(self):
        """Two replicas fed the same seeded latency stream make the SAME
        selection sequence and both converge on the fastest arm — the
        property the cluster-wide lockstep swap rests on."""
        from kungfu_tpu.policy.bandit import ArmStats

        lat = {"a": 0.10, "b": 0.04, "c": 0.20}

        def run():
            t = ArmStats(("a", "b", "c"), min_pulls=2)
            rng = random.Random(7)
            seq = []
            for _ in range(60):
                arm = t.select()
                seq.append(arm)
                t.observe(arm, lat[arm] + rng.random() * 0.005)
            return seq

        s1, s2 = run(), run()
        assert s1 == s2, "identical streams must make identical decisions"
        assert set(s1[-10:]) == {"b"}, f"did not converge: {s1[-10:]}"
        # exploration visited every arm at least min_pulls times
        assert all(s1.count(a) >= 2 for a in ("a", "b", "c"))

    def test_unexplored_first_in_declaration_order(self):
        from kungfu_tpu.policy.bandit import ArmStats

        t = ArmStats(("x", "y", "z"), min_pulls=1)
        assert t.select() == "x"
        t.observe("x", 1.0)
        assert t.select() == "y"
        t.observe("y", 1.0)
        assert t.select() == "z"

    def test_reset_reexplores(self):
        from kungfu_tpu.policy.bandit import ArmStats

        t = ArmStats(("x", "y"))
        t.observe("x", 0.1)
        t.observe("y", 0.2)
        assert t.unexplored() is None
        t.reset()
        assert t.unexplored() == "x"
        assert t.mean("x") is None

    def test_rejects_uncredible_observations(self):
        """A 0-count, negative, or non-finite sample is the startup-probe
        failure mode (ROADMAP #4) — rejected loudly, never folded."""
        from kungfu_tpu.policy.bandit import ArmStats

        t = ArmStats(("x",))
        with pytest.raises(ValueError):
            t.observe("x", float("nan"))
        with pytest.raises(ValueError):
            t.observe("x", -1.0)
        with pytest.raises(ValueError):
            t.observe("x", 0.0)  # a 0 s mean would be unbeatable forever
        with pytest.raises(ValueError):
            t.observe("x", 0.1, count=0)
        with pytest.raises(KeyError):
            t.observe("nope", 0.1)

    def test_degraded_incumbent_is_abandoned(self):
        """Non-stationarity: once the converged winner's measurements
        degrade, UCB moves off it within a few windows."""
        from kungfu_tpu.policy.bandit import ArmStats

        t = ArmStats(("fast", "slow"), min_pulls=1)
        for _ in range(6):
            t.observe(t.select(), 0.01 if t.select() == "fast" else 0.05)
        # interference hits the incumbent
        for _ in range(20):
            arm = t.select()
            t.observe(arm, 0.5 if arm == "fast" else 0.05)
        assert t.select() == "slow"


class TestScheduleTable:
    def test_buckets_learn_independent_winners(self):
        from kungfu_tpu.policy.bandit import ScheduleTable

        st = ScheduleTable(("psum", "ring"), n_buckets=2, min_pulls=1)
        for _ in range(8):
            st.observe(0, "psum", 0.001)
            st.observe(0, "ring", 0.010)
            st.observe(1, "psum", 0.100)
            st.observe(1, "ring", 0.020)
        assert st.select(0) == "psum"
        assert st.select(1) == "ring"
        st.install(0, "psum")
        st.install(1, "ring")
        assert st.active == ["psum", "ring"]
        with pytest.raises(KeyError):
            st.install(0, "bogus")

    def test_size_bucket_edges(self):
        from kungfu_tpu.ops.schedules import (SIZE_BUCKET_EDGES,
                                              SIZE_BUCKETS, size_bucket)

        assert len(SIZE_BUCKETS) == len(SIZE_BUCKET_EDGES) + 1
        assert size_bucket(0) == 0
        assert size_bucket(SIZE_BUCKET_EDGES[0] - 1) == 0
        assert size_bucket(SIZE_BUCKET_EDGES[0]) == 1
        assert size_bucket(1 << 30) == len(SIZE_BUCKETS) - 1


class TestDeviceBucketDispatch:
    @pytest.fixture
    def comm(self):
        import jax

        from kungfu_tpu.comm.device import Communicator

        return Communicator(devices=jax.devices()[:4], local_size=4)

    def test_per_bucket_strategy_dispatch(self, comm):
        """Small and large payloads ride independently-installed
        schedules; values stay identical to psum."""
        small = np.arange(4, dtype=np.float32)[:, None]
        large = np.ones((4, 100_000), np.float32)
        comm.set_bucket_strategy(1, "ring")
        out_s = np.asarray(comm.all_reduce(small))
        out_l = np.asarray(comm.all_reduce(large))
        assert float(out_s[0, 0]) == 6.0
        assert np.all(out_l == 4.0)
        assert comm.strategy_for(small.nbytes // 4) == "psum"
        assert comm.strategy_for(large.nbytes) == "ring"
        # the compiled-program cache carries the per-bucket schedule
        scheds = {k[5] for k in comm._fns if k[0] == "ar"}
        assert {"psum", "ring"} <= scheds
        assert comm.bucket_summary() == "large=ring"
        comm.set_bucket_strategy(1, None)
        assert comm.bucket_summary() == ""
        assert comm.strategy_for(large.nbytes) == "psum"
        with pytest.raises(ValueError):
            comm.set_bucket_strategy(0, "bogus")
        with pytest.raises(ValueError):
            comm.set_bucket_strategy(99, "ring")

    def test_latency_hook_reports_executed_schedule(self, comm):
        obs = []
        comm.set_latency_hook(lambda n, s, dt: obs.append((n, s, dt)))
        comm.set_bucket_strategy(1, "two_stage")
        comm.all_reduce(np.arange(4, dtype=np.float32)[:, None])
        comm.all_reduce(np.ones((4, 100_000), np.float32))
        comm.set_latency_hook(None)
        assert [(n, s) for n, s, _ in obs] == [
            (16, "psum"), (1_600_000, "two_stage")]
        assert all(dt >= 0 for _, _, dt in obs)
        # hook removed: no further observations
        comm.all_reduce(np.arange(4, dtype=np.float32)[:, None])
        assert len(obs) == 2

    def test_autotune_rejects_uncredible_winner(self, comm, monkeypatch):
        """The satellite-1 guard: a 0.0 s / non-finite winning time keeps
        the incumbent instead of installing a coin-flip."""
        comm.set_strategy("two_stage")
        for bad in ([0.0, 0.0, 0.0],          # 0.0 s winner
                    [float("nan")] * 3,       # -> 1e9 sentinels
                    [1e9, 1e9, 1e9]):         # nothing really timed
            monkeypatch.setattr(
                type(comm), "_time_schedules",
                lambda self, x, trials, _bad=bad: list(_bad))
            assert comm.autotune_strategy(nbytes=1 << 10,
                                          trials=1) == "two_stage"
            assert comm.strategy == "two_stage"

    def test_device_driver_converges_and_installs(self, comm):
        """Single-controller device bandit: explores every (bucket, arm),
        then installs winners into the communicator's bucket table."""
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver

        d = DeviceBanditDriver(comm, check_every=2, min_pulls=1)
        small = np.arange(4, dtype=np.float32)[:, None]
        large = np.ones((4, 50_000), np.float32)
        swaps = 0
        for _ in range(18):
            comm.all_reduce(small)
            comm.all_reduce(large)
            if d.step():
                swaps += 1
        assert swaps > 0, "exploration never installed a bucket override"
        summary = d.summary()
        assert set(summary) == {0, 1}
        # every arm of every bucket was measured at least once
        for b in summary.values():
            assert all(v["count"] > 0 for v in b["arms"].values()), summary
        # the communicator reflects the driver's installed table
        for b, active in enumerate(d.table.active):
            assert comm.strategy_for_bucket(b) == active
        comm.set_latency_hook(None)

    def test_device_driver_timeline_feed(self, comm, monkeypatch):
        """``feed="timeline"``: the per-schedule ring is fed from the
        flight recorder's device spans (which carry nbytes/sched)."""
        from kungfu_tpu.monitor import timeline
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver

        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        timeline.reset()
        d = DeviceBanditDriver(comm, check_every=4, feed="timeline")
        assert comm._latency_hook is None  # timeline mode installs none
        comm.all_reduce(np.ones((4, 100_000), np.float32))
        comm.all_reduce(np.arange(4, dtype=np.float32)[:, None])
        assert d.feed_from_timeline() == 2
        pend = d._pending
        assert sum(c for c, _ in pend[1].values()) == 1  # large span
        assert sum(c for c, _ in pend[0].values()) == 1  # small span
        timeline.reset()


class TestEngineSwapEpochs:
    def test_window_peek_and_swap_eligibility(self):
        """window_peek is non-destructive (unlike throughputs) and the
        swap-eligibility epoch counts collectives since mark_swap."""
        from kungfu_tpu.comm.engine import CollectiveEngine
        from kungfu_tpu.comm.host import PyHostChannel
        from kungfu_tpu.plan import PeerID, PeerList, Strategy

        peers = PeerList.of(PeerID("127.0.0.1", 27531),
                            PeerID("127.0.0.1", 27532))
        chans = [PyHostChannel(p, bind_host="127.0.0.1") for p in peers]
        engines = [CollectiveEngine(c, peers, Strategy.STAR)
                   for c in chans]
        try:
            data = np.ones(1000, np.float32)
            run_all([lambda e=e: e.all_reduce(data) for e in engines])
            e = engines[0]
            w1 = e.window_peek()
            w2 = e.window_peek()
            assert w1 == w2 and sum(b for b, _ in w1) > 0
            assert e.throughputs()  # destructive reset
            assert sum(b for b, _ in e.window_peek()) == 0
            assert e.collectives_since_swap() >= 1
            assert e.swap_eligible(1)
            e.mark_swap()
            assert e.collectives_since_swap() == 0
            assert not e.swap_eligible(1)
            assert e.swap_eligible(0)
        finally:
            for e in engines:
                e.close()
            for c in chans:
                c.close()


def _make_peers(base_port, strategy="STAR", n=3, config_server=None):
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.plan import Cluster, PeerList, parse_strategy
    from kungfu_tpu.utils.envs import Config

    workers = PeerList.parse(
        ",".join(f"127.0.0.1:{base_port + i}" for i in range(n)))
    runners = PeerList.parse(f"127.0.0.1:{base_port + 99}")
    cluster = Cluster(runners, workers)
    ps = [Peer(Config(self_id=w, cluster=cluster,
                      config_server=config_server)) for w in workers]
    for p in ps:
        p.config.strategy = parse_strategy(strategy)
        p.start()
    return ps


class TestFencedSwapLockstep:
    """3-rank in-process cluster: every rank must reach the same swap
    decision at the same step from DIVERGENT local measurements (the
    window exchange is an allreduce; the decision is pure)."""

    @pytest.fixture
    def peers(self, monkeypatch):
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        ps = _make_peers(27501)
        yield ps
        for p in ps:
            p.close()

    def test_lockstep_swap_and_event_on_every_rank(self, peers, monkeypatch):
        from kungfu_tpu.monitor import timeline
        from kungfu_tpu.monitor.adapt_device import HostBanditDriver
        from kungfu_tpu.monitor.registry import REGISTRY

        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        timeline.reset()
        drivers = [
            HostBanditDriver(p, arms=("STAR", "RING"), check_every=2,
                             min_pulls=1, min_swap_collectives=1)
            for p in peers
        ]
        swaps_before = REGISTRY.counter(
            "kf_strategy_swaps_total", what="RING").value

        def one(rank, p, d, step):
            # synthetic measured windows, rank-skewed so locals DISAGREE:
            # STAR reads ~100 ms, RING ~1 ms — only the allreduced mean
            # can make the ranks agree
            dt = (0.1 if d.active == "STAR" else 0.001) * (1 + 0.2 * rank)
            return d.step(dt)

        swap_steps = []
        for step in range(8):
            flags = run_all([
                lambda r=r, p=p, d=d: one(r, p, d, step)
                for r, (p, d) in enumerate(zip(peers, drivers))
            ])
            assert len(set(flags)) == 1, f"non-lockstep at step {step}"
            if flags[0]:
                swap_steps.append(step)
        assert swap_steps, "no swap fired"
        # every rank landed on the same arm, and the engines agree
        actives = {d.active for d in drivers}
        assert len(actives) == 1
        strategies = {getattr(p.engine().strategy, "name", None)
                      for p in peers}
        assert len(strategies) == 1
        # the fence contract: each swap seq has one event per rank
        swaps = [e for e in timeline.snapshot() if e["kind"] == "swap"]
        assert swaps, "swap events missing from the flight recorder"
        by_seq = {}
        for e in swaps:
            by_seq.setdefault(e["attrs"]["seq"], []).append(e["rank"])
        for seq, ranks in by_seq.items():
            assert sorted(ranks) == [0, 1, 2], (seq, ranks)
        # the counted kind ticks the registry even beyond the ring
        assert REGISTRY.counter("kf_strategy_swaps_total",
                                what="RING").value > swaps_before
        timeline.reset()


class TestCollectiveBanditPolicy:
    """The PolicyRunner wiring: the bandit rides the per-step policy
    callbacks, fed by the loop's measured collective seconds."""

    def test_runner_drives_lockstep_swaps(self, monkeypatch):
        from kungfu_tpu.policy import CollectiveBanditPolicy, PolicyRunner

        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        peers = _make_peers(27541)
        try:
            policies = [CollectiveBanditPolicy(
                p, arms=("STAR", "RING"), check_every=2, min_pulls=1,
                min_swap_collectives=1) for p in peers]
            runners = [PolicyRunner([pol], peer=p, batch_size=4)
                       for pol, p in zip(policies, peers)]

            def one(pol, run):
                dt = 0.1 if pol.host.active == "STAR" else 0.001
                run.after_step(step_collective_s=dt)
                return pol.host.active, run.ctx.metrics.get("bandit_swaps")

            last = []
            for _ in range(6):
                last = run_all([lambda pol=pol, run=run: one(pol, run)
                                for pol, run in zip(policies, runners)])
                assert len({a for a, _ in last}) == 1  # lockstep arms
            assert {a for a, _ in last} == {"RING"}
            assert all(s and s >= 1.0 for _, s in last), last
        finally:
            for p in peers:
                p.close()


class TestResizeReexplore:
    """Bandit state across a LIVE resize (3 -> 2 over the real config
    server + consensus protocol, driven by ``elastic_step(bandit=...)``):
    the arm table resets and the new membership re-explores."""

    def test_live_shrink_resets_bandit(self, monkeypatch):
        from kungfu_tpu.elastic import ConfigServer
        from kungfu_tpu.elastic.hooks import ElasticState, elastic_step
        from kungfu_tpu.monitor.adapt_device import HostBanditDriver
        from kungfu_tpu.plan import Cluster, PeerList

        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        workers = PeerList.parse(
            ",".join(f"127.0.0.1:{27511 + i}" for i in range(3)))
        runners = PeerList.parse("127.0.0.1:27610")
        server = ConfigServer(port=29141,
                              cluster=Cluster(runners, workers)).start()
        peers = _make_peers(27511,
                            config_server="http://127.0.0.1:29141/get")
        drivers = [HostBanditDriver(p, arms=("STAR", "RING"), check_every=2,
                                    min_pulls=1, min_swap_collectives=1)
                   for p in peers]
        params = {"w": np.arange(4.0, dtype=np.float32)}
        # 3 workers until step 3, then 2 (a live planned shrink)
        schedule = "3:3,2:100"
        try:
            def loop(p, d):
                state = ElasticState()
                out = dict(resets=0, stopped=False, size=p.size())
                for _ in range(6):
                    counts_before = sum(d.table.counts)
                    state, _, stop = elastic_step(
                        p, state, schedule, params, bandit=d)
                    if stop:
                        out["stopped"] = True
                        break
                    d.step(0.01)
                    if counts_before > 0 and sum(d.table.counts) == 0:
                        out["resets"] += 1
                out["size"] = p.size()
                out["version"] = d._seen_version
                return out

            outs = run_all(
                [lambda p=p, d=d: loop(p, d)
                 for p, d in zip(peers, drivers)], timeout=180)
            stopped = [o for o in outs if o["stopped"]]
            survived = [o for o in outs if not o["stopped"]]
            assert len(stopped) == 1 and len(survived) == 2, outs
            # the survivors crossed the resize: state was reset at least
            # once and the drivers track the new cluster version
            assert all(o["size"] == 2 for o in survived)
            assert all(o["resets"] >= 1 for o in survived), outs
            versions = {o["version"] for o in survived}
            assert len(versions) == 1 and versions != {0}
            # post-resize the table re-explores from scratch
            for d, o in zip(drivers, outs):
                if not o["stopped"]:
                    assert sum(d.table.counts) < 4  # only fresh windows
        finally:
            for p in peers:
                p.close()
            server.stop()


class TestChaosDelayAbandon:
    """The satellite chaos run: ``delay`` clauses degrade the 0<->1 link;
    the policy must abandon the degraded starting strategy."""

    def test_bandit_abandons_degraded_strategy(self, monkeypatch):
        from kungfu_tpu import chaos
        from kungfu_tpu.monitor.adapt_device import HostBanditDriver

        wire_ms = 15
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        monkeypatch.setenv("KF_CHAOS_SPEC", ";".join(
            f"delay:ms={wire_ms},rank={a},peer={b},on={on}"
            for a, b in ((0, 1), (1, 0)) for on in ("send", "ping")))
        chaos.reset()
        peers = _make_peers(27521)
        data = np.ones(20_000, np.float32)
        try:
            drivers = [HostBanditDriver(p, check_every=2, min_pulls=1,
                                        min_swap_collectives=1)
                       for p in peers]

            def one(p, d):
                t0 = time.perf_counter()
                out = p.engine().all_reduce(data, op="sum")
                dt = time.perf_counter() - t0
                assert float(out[0]) == 3.0
                return dt, d.step(dt)

            # run PAST the exploration phase (4 arms x check_every=2 x
            # observe+settle) so the tail medians measure the converged
            # arm, not a mid-exploration one — every non-mst arm pays
            # the link delay, so an early cut would compare noise
            times, swapped_at = [], None
            for i in range(24):
                outs = run_all([lambda p=p, d=d: one(p, d)
                                for p, d in zip(peers, drivers)])
                flags = {s for _, s in outs}
                assert len(flags) == 1, f"non-lockstep at {i}"
                times.append(max(t for t, _ in outs))
                if flags.pop() and swapped_at is None:
                    swapped_at = i
            assert swapped_at is not None, "policy never abandoned STAR"
            actives = {d.active for d in drivers}
            assert len(actives) == 1 and actives != {"STAR"}, actives
            # and the adaptation paid off: the converged tail beats the
            # degraded opening phase (only the MST tree dodges the
            # throttled 0<->1 edge, by ~10x — ample noise margin)
            degraded = float(np.median(times[:swapped_at + 1]))
            steady = float(np.median(times[-3:]))
            assert steady < degraded, (degraded, steady)
        finally:
            for p in peers:
                p.close()
            chaos.reset()

    def test_delay_on_ping_inflates_latency_probe(self, monkeypatch):
        """``on=ping`` reaches get_peer_latencies — the MST re-carve must
        see the same interference the data path pays."""
        from kungfu_tpu import chaos
        from kungfu_tpu.monitor.adapt import get_peer_latencies

        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "delay:ms=60,rank=0,peer=1,on=ping")
        chaos.reset()
        peers = _make_peers(27526, n=2)
        try:
            row = get_peer_latencies(peers[0], samples=1)
            assert row[0] == 0.0
            assert row[1] >= 0.055, row
        finally:
            for p in peers:
                p.close()
            chaos.reset()


class TestHostPoolScaling:
    def test_scales_with_peer_count_capped_and_gauged(self, monkeypatch):
        from kungfu_tpu.comm.host import host_pool_size
        from kungfu_tpu.monitor.registry import REGISTRY

        assert host_pool_size(2) == 2
        assert host_pool_size(1) == 2          # floor
        assert host_pool_size(10) == 10
        assert host_pool_size(500) == 16       # default cap
        assert REGISTRY.gauge("kf_host_pool_size", pool="host").value == 16
        monkeypatch.setenv("KF_CONFIG_HOST_POOL_MAX", "4")
        assert host_pool_size(10) == 4
        # the operator's cap wins over any caller floor (a
        # thread-constrained host must be able to bound the engine pool)
        assert host_pool_size(10, floor=8, pool="engine") == 4
        assert REGISTRY.gauge("kf_host_pool_size", pool="engine").value == 4
        monkeypatch.setenv("KF_CONFIG_HOST_POOL_MAX", "0")
        assert host_pool_size(10) >= 1         # nonsense cap stays sane

    def test_p2p_responder_pool_scales_with_peers(self, monkeypatch):
        """install_p2p_handler sizes the responder pool from the peer
        count (env override still pins it)."""
        from kungfu_tpu.store.p2p import install_p2p_handler

        class FakeChan:
            def on_p2p_request(self, h):
                self.handler = h

        def n_responders():
            return sum(1 for t in threading.enumerate()
                       if t.is_alive()
                       and t.name.startswith("kf-p2p-responder"))

        monkeypatch.delenv("KF_CONFIG_P2P_RESPONDERS", raising=False)
        before = n_responders()
        stop = install_p2p_handler(FakeChan(), store={}, n_peers=6)
        try:
            assert n_responders() - before == 6
        finally:
            stop()
        monkeypatch.setenv("KF_CONFIG_P2P_RESPONDERS", "3")
        before = n_responders()
        stop = install_p2p_handler(FakeChan(), store={}, n_peers=12)
        try:
            assert n_responders() - before == 3
            # the gauge reflects the PINNED size too
            from kungfu_tpu.monitor.registry import REGISTRY

            assert REGISTRY.gauge("kf_host_pool_size",
                                  pool="p2p").value == 3
        finally:
            stop()


class TestPallasRingArm:
    """ISSUE 12: ``pallas_ring`` — the in-kernel-overlap ICI ring of
    ``ops/pallas/collectives.py`` — as a first-class device-bandit arm:
    default arm set, per-bucket install through the consensus-fenced
    lockstep swap, and the reset-on-resize contract."""

    def test_pallas_ring_in_default_arm_set(self):
        import jax

        from kungfu_tpu.comm.device import Communicator
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver

        comm = Communicator(devices=jax.devices()[:4], local_size=4)
        d = DeviceBanditDriver(comm, check_every=2)
        assert "pallas_ring" in d.table.arms
        comm.set_latency_hook(None)

    def test_pallas_ring_installs_per_bucket(self):
        """Synthetic latencies make pallas_ring the measured winner of
        the LARGE bucket only: the driver installs it there via
        set_bucket_strategy and leaves the small bucket alone."""
        import jax

        from kungfu_tpu.comm.device import Communicator
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver

        comm = Communicator(devices=jax.devices()[:4], local_size=4)
        d = DeviceBanditDriver(comm, check_every=1, min_pulls=1)
        lat = {"psum": 0.05, "two_stage": 0.04, "ring": 0.06,
               "pallas_ring": 0.001}
        small, large = 1 << 10, 1 << 20
        for _ in range(12):
            # both buckets measure every arm: pallas_ring wins the
            # large payloads, psum the latency-bound small ones
            for arm, t in lat.items():
                d._on_collective(large, arm, t)
                d._on_collective(small, arm,
                                 0.0001 if arm == "psum" else 0.01)
            d.step()
        assert comm.strategy_for_bucket(1) == "pallas_ring"
        assert d.table.active[1] == "pallas_ring"
        assert comm.strategy_for_bucket(0) == "psum"
        # the installed arm really routes: a large eager collective now
        # compiles the pallas_ring schedule (cache key carries it)
        x = np.random.default_rng(0).standard_normal((4, large // 4)) \
            .astype(np.float32)
        out = np.asarray(comm.all_reduce(x))
        np.testing.assert_allclose(
            out, np.broadcast_to(x.sum(0), x.shape), rtol=1e-4, atol=1e-4)
        assert any(k[-1] == "pallas_ring" for k in comm._fns
                   if k[0] == "ar"), list(comm._fns)
        comm.set_latency_hook(None)

    def test_fenced_lockstep_install_across_ranks(self, monkeypatch):
        """3-rank in-process cluster, each rank owning its own device
        communicator + driver: identical window exchanges must install
        pallas_ring on EVERY rank at the same seq, through the
        consensus_bytes digest + barrier fence."""
        import jax

        from kungfu_tpu.comm.device import Communicator
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver

        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        peers = _make_peers(27561)
        try:
            comms = [Communicator(devices=jax.devices()[:4], local_size=4)
                     for _ in peers]
            drivers = [DeviceBanditDriver(c, peer=p, check_every=2,
                                          min_pulls=1)
                       for c, p in zip(comms, peers)]

            def one(rank, d):
                # rank-skewed locals (only the allreduced window can
                # agree), pallas_ring clearly fastest on large payloads
                skew = 1 + 0.3 * rank
                for arm, t in (("psum", 0.05), ("two_stage", 0.04),
                               ("ring", 0.06), ("pallas_ring", 0.002)):
                    d._on_collective(1 << 20, arm, t * skew)
                return d.step()

            for step in range(10):
                flags = run_all([
                    lambda r=r, d=d: one(r, d)
                    for r, d in enumerate(drivers)
                ], timeout=120)
                assert len(set(flags)) == 1, f"non-lockstep at {step}"
            installed = {c.strategy_for_bucket(1) for c in comms}
            assert installed == {"pallas_ring"}, installed
            seqs = {d._seq for d in drivers}
            assert len(seqs) == 1
        finally:
            for p in peers:
                p.close()

    def test_reset_on_live_resize(self):
        """A mesh-epoch rebuild (the resize simulation the strategy
        tests use: retire the communicator, bump the version) rebinds
        the driver, zeroes every bucket table, and drops the installed
        pallas_ring override — a new membership is a new regime."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.monitor.adapt_device import DeviceBanditDriver
        from kungfu_tpu.utils import envs as E

        peer = Peer(config=E.parse_config_from_env({}))
        comm0 = peer.communicator()
        d = DeviceBanditDriver(comm0, peer=peer, check_every=1,
                               min_pulls=1)
        for _ in range(6):
            for arm, t in (("psum", 0.05), ("two_stage", 0.04),
                           ("ring", 0.06), ("pallas_ring", 0.001)):
                d._on_collective(1 << 20, arm, t)
            d.step()
        assert comm0.strategy_for_bucket(1) == "pallas_ring"
        assert sum(d.table.tables[1].counts) > 0
        with peer._lock:
            peer._retire_comm()
        peer.cluster_version += 1
        d.step()  # detects the version move and rebinds
        comm1 = peer.communicator()
        assert d.comm is comm1 and comm1 is not comm0
        # re-explore from scratch on the new epoch: table zeroed, no
        # bucket override carried (deliberately NOT persisted — the
        # bandit must re-measure the new regime)
        assert sum(sum(t.counts) for t in d.table.tables) == 0
        assert comm1.bucket_strategies() == {}
        assert d.table.active[1] == comm1.strategy_for_bucket(1)
