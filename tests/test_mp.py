"""launch_multiprocess: the programmatic single-machine launcher
(reference ``kungfu.cmd.launch_multiprocess`` + ``SingleMachineEnv``)."""

import numpy as np
import pytest

# every test here spawns real worker processes
pytestmark = pytest.mark.slow


def _worker(rank, size):
    import kungfu_tpu as kf

    peer = kf.init()
    assert kf.current_rank() == rank
    assert kf.cluster_size() == size
    eng = peer.engine()
    out = eng.all_reduce(np.full(4, float(rank + 1), np.float32))
    expect = size * (size + 1) / 2
    assert np.allclose(out, expect), (rank, out)
    kf.finalize()


def _worker_with_args(rank, size, base, scale=1):
    assert base == 7 and scale == 3, (base, scale)


def _crasher(rank, size):
    if rank == 1:
        raise SystemExit(3)


def _crash_while_peer_collects(rank, size):
    """Rank 1 dies pre-collective; rank 0 blocks in an allreduce waiting
    for it — the launcher must fail fast, not ride out the timeout."""
    import kungfu_tpu as kf

    if rank == 1:
        raise SystemExit(3)
    peer = kf.init()
    peer.engine().all_reduce(np.ones(4, np.float32))


class TestLaunchMultiprocess:
    def test_cluster_forms_and_allreduces(self):
        from kungfu_tpu import launch_multiprocess

        launch_multiprocess(_worker, 2, timeout=120)

    def test_args_kwargs_forwarded(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        launch_multiprocess(_worker_with_args, 2, 7, scale=3, timeout=60)

    def test_worker_failure_raises(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        with pytest.raises(RuntimeError, match="exited with code 3"):
            launch_multiprocess(_crasher, 2, timeout=60)

    def test_fail_fast_terminates_blocked_survivors(self):
        """A crashed worker must take the launch down promptly even while
        a survivor is blocked in a collective waiting for it."""
        import time

        from kungfu_tpu.runner.mp import launch_multiprocess

        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="exited with code 3"):
            launch_multiprocess(_crash_while_peer_collects, 2, timeout=120)
        assert time.monotonic() - t0 < 60, "fail-fast did not engage"

    def test_bad_np_rejected(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        with pytest.raises(ValueError):
            launch_multiprocess(_worker, 0)
