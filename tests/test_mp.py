"""launch_multiprocess: the programmatic single-machine launcher
(reference ``kungfu.cmd.launch_multiprocess`` + ``SingleMachineEnv``)."""

import numpy as np
import pytest

# every test here spawns real worker processes
pytestmark = pytest.mark.slow


def _worker(rank, size):
    import kungfu_tpu as kf

    peer = kf.init()
    assert kf.current_rank() == rank
    assert kf.cluster_size() == size
    eng = peer.engine()
    out = eng.all_reduce(np.full(4, float(rank + 1), np.float32))
    expect = size * (size + 1) / 2
    assert np.allclose(out, expect), (rank, out)
    kf.finalize()


def _worker_with_args(rank, size, base, scale=1):
    assert base == 7 and scale == 3, (base, scale)


def _crasher(rank, size):
    if rank == 1:
        raise SystemExit(3)


def _crash_while_peer_collects(rank, size):
    """Rank 1 dies pre-collective; rank 0 blocks in an allreduce waiting
    for it — the launcher must fail fast, not ride out the timeout."""
    import kungfu_tpu as kf

    if rank == 1:
        raise SystemExit(3)
    peer = kf.init()
    peer.engine().all_reduce(np.ones(4, np.float32))


class TestLaunchMultiprocess:
    def test_cluster_forms_and_allreduces(self):
        from kungfu_tpu import launch_multiprocess

        launch_multiprocess(_worker, 2, timeout=120)

    def test_args_kwargs_forwarded(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        launch_multiprocess(_worker_with_args, 2, 7, scale=3, timeout=60)

    def test_worker_failure_raises(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        with pytest.raises(RuntimeError, match="exited with code 3"):
            launch_multiprocess(_crasher, 2, timeout=60)

    def test_fail_fast_terminates_blocked_survivors(self):
        """A crashed worker must take the launch down promptly even while
        a survivor is blocked in a collective waiting for it."""
        import time

        from kungfu_tpu.runner.mp import launch_multiprocess

        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="exited with code 3"):
            launch_multiprocess(_crash_while_peer_collects, 2, timeout=120)
        assert time.monotonic() - t0 < 60, "fail-fast did not engage"

    def test_bad_np_rejected(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        with pytest.raises(ValueError):
            launch_multiprocess(_worker, 0)


def _zero2_vs_replicated_worker(rank, size):
    """ZeRO-2 host-plane step (reduce_scatter -> chunk update ->
    all_gather) vs the replicated all-reduce step, bitwise, on exact
    binary-fraction inputs — the step-equivalence claim on a REAL
    multi-process world."""
    import math

    import numpy as np

    import kungfu_tpu as kf

    peer = kf.init()
    eng = peer.engine()
    n, me = size, rank
    total = 10
    # grads: exact binary fractions, distinct per rank
    g_local = (np.arange(total, dtype=np.float32) + rank) * 0.25
    p0 = np.arange(total, dtype=np.float32) / 8.0

    # replicated path: all-reduce mean, full update everywhere
    g_full = eng.all_reduce(g_local, op="mean", name="zr.ar")
    p_rep = p0 - 0.5 * g_full

    # zero2 path: reduce-scatter mean, update own chunk, all-gather
    chunk = math.ceil(total / n)
    g_chunk = eng.reduce_scatter(g_local, op="mean", name="zr.rs")
    padded = np.zeros(chunk * n, np.float32)
    padded[:total] = p0
    p_chunk = padded[me * chunk:(me + 1) * chunk] - 0.5 * g_chunk
    p_zero = eng.all_gather(p_chunk, name="zr.ag").reshape(-1)[:total]

    np.testing.assert_array_equal(p_zero, p_rep)
    kf.finalize()


class TestZero2HostPlane:
    def test_step_equivalence_bitwise_2proc(self):
        from kungfu_tpu.runner.mp import launch_multiprocess

        launch_multiprocess(_zero2_vs_replicated_worker, 2, timeout=120)

    def test_step_equivalence_bitwise_3proc(self):
        """n=3: the padded chunk geometry (10 over 3) is live."""
        from kungfu_tpu.runner.mp import launch_multiprocess

        launch_multiprocess(_zero2_vs_replicated_worker, 3, timeout=120)
