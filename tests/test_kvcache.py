"""Paged KV-cache block manager invariants (kf-serve, pure unit).

The pool is the serving plane's memory system: admission control is
only as real as these invariants — a freed page served to a live
request is silent cross-request corruption, and a wrong footprint gauge
lies to the autoscaler.  Everything here runs without jax.
"""

import numpy as np
import pytest

from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.serve.kvcache import (CacheExhausted, KVCachePool, PageSpec,
                                      chain_hashes)

SPEC = PageSpec(n_layers=2, n_heads=2, head_dim=4, page_tokens=4,
                dtype="float32")


def _data(seed=0):
    rng = np.random.default_rng(seed)
    shape = (SPEC.n_layers, SPEC.n_heads, SPEC.page_tokens, SPEC.head_dim)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


class TestSpec:
    def test_page_bytes(self):
        # 2 (K+V) * 2 layers * 2 heads * 4 tokens * 4 dim * 4 bytes
        assert SPEC.page_bytes == 2 * 2 * 2 * 4 * 4 * 4

    def test_chain_hashes_only_full_pages(self):
        assert chain_hashes([1, 2, 3], 4) == []
        assert len(chain_hashes([1, 2, 3, 4, 5], 4)) == 1
        assert len(chain_hashes(list(range(8)), 4)) == 2

    def test_chain_property(self):
        """Digest i covers the WHOLE prefix: two sequences agreeing only
        on page 1's local tokens must not share page 1."""
        a = chain_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
        b = chain_hashes([9, 9, 9, 9, 5, 6, 7, 8], 4)
        assert a[0] != b[0]
        assert a[1] != b[1]  # same local tokens, different context
        c = chain_hashes([1, 2, 3, 4, 5, 6, 7, 8, 0], 4)
        assert c[:2] == a[:2]  # true shared prefix DOES share


class TestAllocation:
    def test_alloc_release_round_trip_and_gauge(self):
        pool = KVCachePool(SPEC, capacity_pages=8)
        assert pool.footprint_bytes == 0
        pages = pool.alloc(3)
        assert len(set(pages)) == 3
        assert pool.footprint_bytes == 3 * SPEC.page_bytes
        assert REGISTRY.gauge("kf_kv_cache_bytes").value == 3 * SPEC.page_bytes
        pool.release(pages)
        assert pool.footprint_bytes == 0
        assert REGISTRY.gauge("kf_kv_cache_bytes").value == 0
        assert pool.free_pages == 8

    def test_all_or_nothing(self):
        pool = KVCachePool(SPEC, capacity_pages=4)
        held = pool.alloc(3)
        with pytest.raises(CacheExhausted):
            pool.alloc(2)
        # the failed alloc moved nothing
        assert pool.free_pages == 1
        pool.release(held)

    def test_double_release_raises(self):
        pool = KVCachePool(SPEC, capacity_pages=4)
        pages = pool.alloc(1)
        pool.release(pages)
        with pytest.raises(ValueError):
            pool.release(pages)


class TestPrefixReuse:
    def test_commit_then_lookup_shares_pages(self):
        pool = KVCachePool(SPEC, capacity_pages=8)
        tokens = [1, 2, 3, 4, 5, 6, 7, 8, 9]  # 2 full pages + 1 spare
        pages = pool.alloc(3)
        for i in range(2):
            k, v = _data(i)
            pool.put_page_data(pages[i], k, v)
        assert pool.commit_chain(tokens[:8], pages[:2]) == 2
        pool.release(pages)
        assert pool.cached_pages == 2  # parked, not freed
        got, n = pool.lookup(tokens)
        assert n == 8 and got == pages[:2]
        k0, _ = pool.page_data(got[0])
        np.testing.assert_array_equal(k0, _data(0)[0])
        # retained under the caller: refcounts live again
        assert pool.live_refs() == {pages[0]: 1, pages[1]: 1}
        pool.release(got)

    def test_lookup_stops_at_divergence(self):
        pool = KVCachePool(SPEC, capacity_pages=8)
        tokens = list(range(8))
        pages = pool.alloc(2)
        for i in range(2):
            pool.put_page_data(pages[i], *_data(i))
        pool.commit_chain(tokens, pages)
        pool.release(pages)
        got, n = pool.lookup([0, 1, 2, 3, 99, 5, 6, 7])
        assert n == 4 and got == pages[:1]
        pool.release(got)

    def test_commit_dedupes_first_writer_wins(self):
        pool = KVCachePool(SPEC, capacity_pages=8)
        tokens = list(range(4))
        a = pool.alloc(1)
        pool.put_page_data(a[0], *_data(0))
        assert pool.commit_chain(tokens, a) == 1
        b = pool.alloc(1)
        pool.put_page_data(b[0], *_data(1))
        assert pool.commit_chain(tokens, b) == 0  # incumbent kept
        got, n = pool.lookup(tokens)
        assert got == a
        pool.release(a + b + got)


class TestEviction:
    def test_lru_eviction_of_cold_committed_pages(self):
        pool = KVCachePool(SPEC, capacity_pages=2)
        a = pool.alloc(1)
        pool.put_page_data(a[0], *_data(0))
        pool.commit_chain([1, 2, 3, 4], a)
        pool.release(a)
        b = pool.alloc(1)
        pool.put_page_data(b[0], *_data(1))
        pool.commit_chain([5, 6, 7, 8], b)
        pool.release(b)
        assert pool.cached_pages == 2
        # both free slots are parked caches; a 2-page alloc evicts the
        # OLDEST ([1,2,3,4]) first
        c = pool.alloc(2)
        assert pool.evictions == 2
        assert pool.lookup([1, 2, 3, 4]) == ([], 0)
        assert pool.lookup([5, 6, 7, 8]) == ([], 0)
        pool.release(c)

    def test_referenced_pages_never_evicted(self):
        pool = KVCachePool(SPEC, capacity_pages=2)
        a = pool.alloc(1)
        pool.put_page_data(a[0], *_data(0))
        pool.commit_chain([1, 2, 3, 4], a)
        pool.release(a)
        got, n = pool.lookup([1, 2, 3, 4])  # retained by a "request"
        assert n == 4
        held = pool.alloc(1)
        with pytest.raises(CacheExhausted):
            pool.alloc(1)  # the retained cache page is NOT evictable
        pool.release(got)
        pool.alloc(1)  # now it is
        pool.release(held)


class TestFreedPageNeverLive:
    def test_regression_recycled_page_not_referenced_by_live_request(self):
        """The corruption invariant: across a churny workload, no page
        id ever appears in two live requests' page lists, and a
        released page's id only ever comes back through a fresh alloc
        or a cache hit on committed data."""
        pool = KVCachePool(SPEC, capacity_pages=6)
        rng = np.random.default_rng(7)
        live = {}  # rid -> page list
        for step in range(200):
            if live and rng.random() < 0.45:
                rid = list(live)[int(rng.integers(len(live)))]
                pool.release(live.pop(rid))
            else:
                try:
                    pages = pool.alloc(int(rng.integers(1, 3)))
                except CacheExhausted:
                    continue
                live[f"r{step}"] = pages
            # no page is held by two live requests
            flat = [p for ps in live.values() for p in ps]
            assert len(flat) == len(set(flat)), f"shared page at {step}"
            # pool refcounts agree exactly with what requests hold
            assert pool.live_refs() == {p: 1 for p in flat}
        for ps in live.values():
            pool.release(ps)
        assert pool.footprint_bytes == 0
