"""Chaos-driven fault-tolerance tests (tier-1).

The acceptance scenario of the fault-injection work: a worker killed
mid-allreduce must NOT take the job down — the survivors detect it
(typed ``PeerFailureError`` with a suspect rank instead of a hang), run
the exclusion consensus, shrink the cluster to themselves, and produce
bitwise-correct results over the shrunk membership, all without a
process relaunch.  Quorum loss falls back to the pre-existing
detector-driven restart.  And with ``KF_CHAOS_SPEC`` unset, every hook
is a no-op and results are byte-identical to the chaos-free build.
"""

import threading
import time

import numpy as np
import pytest

from kungfu_tpu import chaos
from kungfu_tpu.checkpoint import StepSnapshot
from kungfu_tpu.comm.engine import CollectiveEngine
from kungfu_tpu.comm.faults import PeerFailureError, QuorumLostError
from kungfu_tpu.comm.host import HostChannel
from kungfu_tpu.plan import Cluster, PeerID, PeerList, Strategy

from tests._util import run_all


@pytest.fixture(autouse=True)
def _fresh_chaos():
    """Cached controllers carry trigger counters across tests that reuse
    a spec string — every test starts from a clean registry."""
    chaos.reset()
    yield
    chaos.reset()


def make_peers(n, base_port, monkeypatch, config_server=""):
    """n real Peer objects on loopback (python transport: the wire-level
    chaos faults are implemented there)."""
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.utils.envs import Config

    monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
    workers = PeerList.of(*(PeerID("127.0.0.1", base_port + i) for i in range(n)))
    runners = PeerList.parse("127.0.0.1:38087")
    cluster = Cluster(runners, workers)
    peers = [
        Peer(Config(self_id=workers[i], cluster=cluster,
                    strategy=Strategy.STAR, config_server=config_server))
        for i in range(n)
    ]
    for p in peers:
        p.start()
    return workers, peers


class TestSpec:
    def test_parse_roundtrip(self):
        clauses = chaos.parse_spec(
            "die:coll=3,rank=2,mode=raise;reset:send=2,peer=0;"
            "delay:ms=200,jitter=50,every=2;drop_fanout:host=h,count=1;"
            "config_down:after=2,count=3"
        )
        assert [c.kind for c in clauses] == [
            "die", "reset", "delay", "drop_fanout", "config_down"
        ]
        assert clauses[0].get("coll") == 3 and clauses[0].rank == 2

    @pytest.mark.parametrize("bad", [
        "explode:now=1",          # unknown kind
        "die:when=5",             # param not valid for kind
        "delay:ms=fast",          # non-integer
        "die:mode=sideways",      # bad mode
        ";;",                     # no clauses
    ])
    def test_junk_fails_loudly(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)

    def test_rank_scoping(self):
        clauses = chaos.parse_spec("die:coll=1,rank=2,mode=raise")
        assert clauses[0].matches_rank(2)
        assert not clauses[0].matches_rank(0)
        assert chaos.parse_spec("delay:ms=1")[0].matches_rank(7)

    def test_delay_every_strides_matching_events(self, monkeypatch):
        """every=K is a stride over CLAUSE-MATCHING events, not the
        global send counter — otherwise the outcome depends on how
        unrelated traffic interleaves (not reproducible)."""
        sleeps = []
        monkeypatch.setattr("kungfu_tpu.chaos.inject.time.sleep",
                            lambda s: sleeps.append(s))
        ctl = chaos.ChaosController(
            chaos.parse_spec("delay:ms=100,peer=1,every=2"), rank=0, seed=0)
        for to in [1, 2, 1, 2, 1, 2, 1]:  # peer-1 sends land on odd turns
            ctl.on_send(to, "x", b"")
        # 4 matching sends to peer 1 -> every 2nd -> exactly 2 delays
        assert len(sleeps) == 2

    def test_seed_determinism(self):
        spec = chaos.parse_spec("delay:ms=1,jitter=100")
        a = chaos.ChaosController(spec, rank=0, seed=7)
        b = chaos.ChaosController(spec, rank=0, seed=7)
        c = chaos.ChaosController(spec, rank=0, seed=8)
        seq = [a._rng.random() for _ in range(4)]
        assert seq == [b._rng.random() for _ in range(4)]
        assert seq != [c._rng.random() for _ in range(4)]


class TestZeroCostWhenDisabled:
    def test_no_controller_without_spec(self, monkeypatch):
        monkeypatch.delenv("KF_CHAOS_SPEC", raising=False)
        assert chaos.controller_for(0) is None
        assert chaos.controller_for(None) is None
        chaos.note_step(0, 5)  # no-op, no error

    def test_allreduce_byte_identical(self, monkeypatch):
        """The acceptance criterion's control arm: chaos disabled, the
        engine takes the exact pre-chaos path (no controller installed)
        and the reduction is bit-exact."""
        monkeypatch.delenv("KF_CHAOS_SPEC", raising=False)
        peers = PeerList.of(PeerID("127.0.0.1", 26520), PeerID("127.0.0.1", 26521))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            assert all(e._chaos is None for e in engines)
            data = [np.arange(64, dtype=np.float32) * (i + 1) for i in range(2)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d)
                            for e, d in zip(engines, data)])
            for o in outs:
                assert np.array_equal(o, data[0] + data[1])
        finally:
            for c in chans:
                c.close()


class TestTypedPeerFailure:
    """The in-flight FT substrate works without chaos: a genuinely dead
    peer surfaces as PeerFailureError naming a suspect, not a hang."""

    def test_recv_deadline_names_the_suspect(self, monkeypatch):
        # python transport: the engine's recv wrapper does the per-peer
        # attribution (the native executor reports rank=None and the
        # recovery driver probes instead)
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "1.5")
        peers = PeerList.of(PeerID("127.0.0.1", 26530), PeerID("127.0.0.1", 26531))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
        chans[1].close()  # rank 1 "dies" before the collective
        try:
            with pytest.raises(PeerFailureError) as ei:
                engines[0].all_reduce(np.ones(4, np.float32))
            assert ei.value.rank == 1
            # the liveness sweep (shrink.find_dead_ranks' primitive)
            # confirms the suspect
            assert not chans[0].ping(peers[1], timeout=1.0)
        finally:
            chans[0].close()


class TestKillOnePeerMidAllreduce:
    """THE acceptance scenario: rank 2 of 3 dies on its 2nd allreduce;
    the survivors shrink to a 2-worker cluster in-process and finish the
    step with bitwise-correct results — no relaunch."""

    def test_shrink_to_survivors(self, monkeypatch):
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=2,rank=2,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_peers(3, 26540, monkeypatch)
        data = [np.arange(32, dtype=np.float32) * (i + 1) for i in range(3)]
        snaps = [StepSnapshot() for _ in range(3)]
        try:
            # step 1: healthy 3-way allreduce, then commit the boundary
            outs = run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            for i, o in enumerate(outs):
                assert np.array_equal(o, data[0] + data[1] + data[2])
                snaps[i].commit(1, {"w": o})

            # step 2: rank 2 dies mid-allreduce
            results = [None] * 3

            def victim():
                try:
                    peers[2].engine().all_reduce(data[2], name="s2")
                    results[2] = ("no-death", None)
                except chaos.InjectedDeath:
                    peers[2].close()  # the process is gone
                    results[2] = ("died", None)

            def survivor(i):
                try:
                    out = peers[i].engine().all_reduce(data[i], name="s2")
                    results[i] = ("clean", out)
                    return
                except PeerFailureError as err:
                    shrunk, replay = peers[i].recover_from_failure(
                        err, snapshot=snaps[i]
                    )
                    assert shrunk, "survivors must agree to shrink"
                    assert replay is not None and replay[0] == 1
                    # replay the interrupted step over the shrunk cluster
                    out = peers[i].engine().all_reduce(data[i], name="s2r")
                    results[i] = ("recovered", out)

            ts = [threading.Thread(target=victim, daemon=True)] + [
                threading.Thread(target=survivor, args=(i,), daemon=True)
                for i in (0, 1)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts), "recovery hung"

            assert results[2][0] == "died"
            want = data[0] + data[1]  # bitwise: survivors-only sum
            for i in (0, 1):
                status, out = results[i]
                assert status == "recovered", results[i]
                assert np.array_equal(out, want)
                assert peers[i].size() == 2
                assert peers[i].cluster_version == 1
                assert not peers[i].detached
        finally:
            for i in (0, 1):
                peers[i].close()

    def test_divergent_committed_steps_adopt_the_leader(self, monkeypatch):
        """The dead peer can feed one survivor before dying, so committed
        steps diverge by one across survivors — recovery must converge on
        ONE agreed (step, state) (the leader's), or the replayed
        collectives rendezvous under mismatched names forever."""
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=1,rank=2,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_peers(3, 26630, monkeypatch)
        snaps = [StepSnapshot() for _ in range(3)]
        # survivor 0 (the future leader) committed step 4; survivor 1 got
        # the victim's last feed and committed step 5 with different state
        snaps[0].commit(4, {"w": np.full(8, 4.0, np.float32)}, {"epoch": 1})
        snaps[1].commit(5, {"w": np.full(8, 5.0, np.float32)}, {"epoch": 1})
        try:
            results = [None] * 2

            def victim():
                try:
                    peers[2].engine().all_reduce(np.ones(8, np.float32))
                except chaos.InjectedDeath:
                    peers[2].close()

            def survivor(i):
                try:
                    peers[i].engine().all_reduce(np.ones(8, np.float32),
                                                 name="x")
                except PeerFailureError as err:
                    results[i] = peers[i].recover_from_failure(
                        err, snapshot=snaps[i]
                    )

            ts = [threading.Thread(target=victim, daemon=True)] + [
                threading.Thread(target=survivor, args=(i,), daemon=True)
                for i in (0, 1)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts)
            for i in (0, 1):
                shrunk, replay = results[i]
                assert shrunk
                step, tree, meta = replay
                # both adopted the LEADER's boundary — including the
                # survivor that was one step ahead
                assert step == 4 and meta == {"epoch": 1}
                assert np.array_equal(tree["w"], np.full(8, 4.0, np.float32))
            assert snaps[1].step() == 4  # stepped back, consistently
        finally:
            for i in (0, 1):
                peers[i].close()

    def test_quorum_loss_falls_back_to_detector(self, monkeypatch):
        """1 survivor of 2 is not a strict majority: shrink must refuse
        (two half-clusters training independently is divergence) and
        escalate to the detector-driven restart path."""
        from kungfu_tpu.monitor.detector import DetectorServer

        detector = DetectorServer(expected_ranks=2, port=27801,
                                  stall_timeout=1.0).start()
        monkeypatch.setenv("KF_MONITOR_ADDR", "127.0.0.1:27801")
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=1,rank=1,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "1.5")
        workers, peers = make_peers(2, 26560, monkeypatch)
        try:
            def victim():
                try:
                    peers[1].engine().all_reduce(np.ones(4, np.float32))
                except chaos.InjectedDeath:
                    peers[1].close()

            t = threading.Thread(target=victim, daemon=True)
            t.start()
            with pytest.raises(PeerFailureError):
                peers[0].engine().all_reduce(np.ones(4, np.float32))
            t.join(10)
            with pytest.raises(QuorumLostError):
                peers[0].recover_from_failure(
                    PeerFailureError(1, workers[1], phase="recv")
                )
            # the escalation signalled the detector (the restart driver)
            deadline = time.time() + 5
            while not detector.results.down_flag and time.time() < deadline:
                time.sleep(0.1)
            assert detector.results.down_flag
        finally:
            peers[0].close()
            detector.stop()

    def test_transient_failure_does_not_shrink(self, monkeypatch):
        """Every worker answers ping => nothing provably died => the
        recovery driver declines to shrink (callers just retry)."""
        workers, peers = make_peers(2, 26580, monkeypatch)
        try:
            shrunk, replay = peers[0].recover_from_failure(
                PeerFailureError(1, workers[1], phase="recv")
            )
            assert not shrunk and replay is None
            assert peers[0].size() == 2  # membership untouched
        finally:
            for p in peers:
                p.close()


class TestShrinkEdgeCases:
    """The boundaries the acceptance scenario skips: survivor sets at
    exactly quorum size, the leader dying during the replay-point
    broadcast, and double-shrink reentry."""

    def test_exact_half_is_not_quorum(self, monkeypatch):
        """4 workers, 2 dead: the survivors are exactly HALF the
        membership — not a strict majority, so the shrink must refuse
        (two half-clusters continuing independently is divergence)."""
        from kungfu_tpu.elastic import shrink

        workers, peers = make_peers(4, 26650, monkeypatch)
        try:
            with pytest.raises(QuorumLostError):
                shrink.shrink_to_survivors(peers[0], [2, 3])
            # refused before any membership change
            assert peers[0].size() == 4
            assert peers[0].cluster_version == 0
        finally:
            for p in peers:
                p.close()

    def test_minimal_strict_majority_shrinks(self, monkeypatch):
        """5 workers, 2 dead: 3 survivors is the smallest strict
        majority — the consensus must run and the shrink must land."""
        from kungfu_tpu.elastic import shrink

        workers, peers = make_peers(5, 26660, monkeypatch)
        try:
            for i in (3, 4):
                peers[i].close()
            results = run_all([
                lambda p=p: shrink.shrink_to_survivors(p, [3, 4])
                for p in peers[:3]
            ])
            assert all(results)
            for p in peers[:3]:
                assert p.size() == 3
                assert p.cluster_version == 1
                assert not p.detached
        finally:
            for p in peers[:3]:
                p.close()

    def test_leader_death_during_replay_broadcast(self, monkeypatch):
        """The shrink agreed but the leader (new rank 0) dies before its
        StepSnapshot broadcast lands: the survivor must come out with
        replay=None (no agreed boundary) and an intact local snapshot —
        not a hang and not a half-adopted state."""
        from kungfu_tpu.elastic import shrink

        workers, peers = make_peers(2, 26670, monkeypatch)
        snap = StepSnapshot()
        snap.commit(7, {"w": np.full(4, 7.0, np.float32)}, {"epoch": 2})
        try:
            # non-leader view: the recv toward the dead leader times out
            def dead_leader_broadcast(*a, **k):
                raise TimeoutError("leader died mid-broadcast")

            monkeypatch.setattr(peers[1].channel, "broadcast_bytes",
                                dead_leader_broadcast)
            assert shrink._sync_replay_point(peers[1], snap) is None
            assert snap.step() == 7  # local boundary untouched
        finally:
            for p in peers:
                p.close()

    def test_leader_side_broadcast_failure_is_contained(self, monkeypatch):
        """Mirror image: the LEADER's sends fail because the followers
        died after voting.  The broadcast error must be contained to
        replay=None, not raised out of the recovery driver."""
        from kungfu_tpu.elastic import shrink

        workers, peers = make_peers(2, 26680, monkeypatch)
        snap = StepSnapshot()
        snap.commit(3, {"w": np.zeros(2, np.float32)})
        try:
            peers[1].close()  # follower gone before the broadcast
            assert shrink._sync_replay_point(peers[0], snap) is None
        finally:
            peers[0].close()

    def test_double_shrink_reentry(self, monkeypatch):
        """Recovery paths re-enter: a second shrink call naming the
        already-evicted rank must be a no-op (stale dead ranks are out
        of range for the shrunk membership), and a genuine second
        failure must escalate through the quorum check."""
        from kungfu_tpu.elastic import shrink

        workers, peers = make_peers(3, 26690, monkeypatch)
        try:
            peers[2].close()
            results = run_all([
                lambda p=p: shrink.shrink_to_survivors(p, [2])
                for p in peers[:2]
            ])
            assert all(results)
            assert peers[0].size() == 2 and peers[0].cluster_version == 1

            # reentry with the stale dead set: rank 2 no longer exists
            assert shrink.shrink_to_survivors(peers[0], [2]) is False
            assert peers[0].size() == 2 and peers[0].cluster_version == 1

            # the driver agrees nothing is dead (ping sweep all-alive)
            shrunk, replay = peers[0].recover_from_failure()
            assert not shrunk and replay is None

            # a genuine second failure: 1 of 2 survivors is no quorum
            peers[1].close()
            with pytest.raises(QuorumLostError):
                peers[0].recover_from_failure(
                    PeerFailureError(1, workers[1], phase="recv")
                )
        finally:
            peers[0].close()


class TestWireFaults:
    def test_reset_mid_chunk_recovered_by_retry(self, monkeypatch):
        """A connection reset halfway through a chunk is a transient: the
        sender's bounded-backoff retry re-sends and the collective
        completes correctly."""
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_CHAOS_SPEC", "reset:send=1,rank=0")
        peers = PeerList.of(PeerID("127.0.0.1", 26600), PeerID("127.0.0.1", 26601))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = [np.arange(1024, dtype=np.float32) * (i + 1) for i in range(2)]
            outs = run_all([lambda e=e, d=d: e.all_reduce(d)
                            for e, d in zip(engines, data)])
            for o in outs:
                assert np.array_equal(o, data[0] + data[1])
        finally:
            for c in chans:
                c.close()

    def test_delay_straggler(self, monkeypatch):
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_CHAOS_SPEC", "delay:ms=300,rank=1")
        peers = PeerList.of(PeerID("127.0.0.1", 26610), PeerID("127.0.0.1", 26611))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            data = [np.full(8, i + 1.0, np.float32) for i in range(2)]
            t0 = time.monotonic()
            outs = run_all([lambda e=e, d=d: e.all_reduce(d)
                            for e, d in zip(engines, data)])
            elapsed = time.monotonic() - t0
            assert elapsed >= 0.25, f"straggler not injected ({elapsed:.3f}s)"
            for o in outs:
                assert np.array_equal(o, data[0] + data[1])
        finally:
            for c in chans:
                c.close()


class TestAsyncHandleFaults:
    """kf-overlap under fire: faults injected mid-flight on an ISSUED
    handle surface as typed ``PeerFailureError`` at ``wait()`` (suspect
    rank attached), and the shrink ladder drains the in-flight window
    before exclusion consensus — ``kf_overlap_inflight`` back to 0, no
    leaked handles (the ISSUE 10 acceptance scenario)."""

    def _gauge(self):
        from kungfu_tpu.monitor.registry import REGISTRY

        return REGISTRY.snapshot().get("kf_overlap_inflight", 0.0)

    def test_delay_midflight_handle_still_completes(self, monkeypatch):
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_CHAOS_SPEC", "delay:ms=300,rank=1")
        peers = PeerList.of(PeerID("127.0.0.1", 26630),
                            PeerID("127.0.0.1", 26631))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR)
                       for c in chans]
            data = [np.full(8, i + 1.0, np.float32) for i in range(2)]
            t0 = time.monotonic()

            def one(i):
                h = engines[i].all_reduce_async(data[i], name="dly")
                out = h.wait(timeout=30)
                assert h.error() is None
                return out

            outs = run_all([lambda i=i: one(i) for i in range(2)])
            assert time.monotonic() - t0 >= 0.25, "straggler not injected"
            for o in outs:
                assert np.array_equal(o, data[0] + data[1])
            assert self._gauge() == 0.0
        finally:
            for c in chans:
                c.close()

    def test_die_midflight_typed_at_wait_and_shrink_drains(self, monkeypatch):
        """Rank 2 of 3 dies on an in-flight async collective.  The
        survivors observe PeerFailureError at wait() of the FIRST
        handle, recover while a SECOND handle is still in flight —
        shrink_to_survivors drains it before the exclusion consensus —
        and finish on the shrunk cluster with the gauge at 0."""
        monkeypatch.setenv("KF_CHAOS_SPEC", "die:coll=2,rank=2,mode=raise")
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "2")
        workers, peers = make_peers(3, 26640, monkeypatch)
        data = [np.arange(32, dtype=np.float32) * (i + 1) for i in range(3)]
        snaps = [StepSnapshot() for _ in range(3)]
        try:
            outs = run_all([
                lambda p=p, d=d: p.engine().all_reduce(d, name="s1")
                for p, d in zip(peers, data)
            ])
            for i, o in enumerate(outs):
                snaps[i].commit(1, {"w": o})

            results = [None] * 3

            def victim():
                # issues ONLY s2: the death fires at its _begin_collective
                # (coll=2), so the victim never contributes to s3 either —
                # both survivor handles are deterministically doomed
                eng = peers[2].engine()
                ha = eng.all_reduce_async(data[2], name="s2")
                try:
                    ha.wait(timeout=30)
                    results[2] = ("no-death", None)
                except chaos.InjectedDeath:
                    peers[2].close()  # the process is gone
                    results[2] = ("died", None)

            def survivor(i):
                eng = peers[i].engine()
                ha = eng.all_reduce_async(data[i], name="s2")
                hb = eng.all_reduce_async(data[i], name="s3")
                try:
                    ha.wait(timeout=30)
                    results[i] = ("clean", None)
                    hb.wait(timeout=30)
                    return
                except PeerFailureError as err:
                    # the typed contract: a suspect rank is attached
                    assert err.rank is not None
                    if i == 0:
                        assert err.rank == 2, err
                    # recover while hb is STILL IN FLIGHT: the shrink
                    # ladder must drain the window before consensus
                    shrunk, replay = peers[i].recover_from_failure(
                        err, snapshot=snaps[i])
                    assert shrunk and replay is not None
                    assert eng.inflight() == 0, "window not drained"
                    assert hb.done(), "drain left hb unsettled"
                    assert isinstance(hb.error(), PeerFailureError)
                    out = peers[i].engine().all_reduce(data[i], name="s2r")
                    results[i] = ("recovered", out)

            ts = [threading.Thread(target=victim, daemon=True)] + [
                threading.Thread(target=survivor, args=(i,), daemon=True)
                for i in (0, 1)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert not any(t.is_alive() for t in ts), "recovery hung"

            assert results[2][0] == "died"
            want = data[0] + data[1]
            for i in (0, 1):
                status, out = results[i]
                assert status == "recovered", results[i]
                assert np.array_equal(out, want)
                assert peers[i].size() == 2
            # no leaked handles anywhere in the process
            assert self._gauge() == 0.0
        finally:
            for i in (0, 1):
                peers[i].close()


class TestControlPlaneFaults:
    def test_config_down_window_then_recovery(self, monkeypatch):
        """fetch_cluster fails for exactly the windowed attempts, then
        the (backed-off) loop converges."""
        from kungfu_tpu.elastic import ConfigServer
        from kungfu_tpu.elastic.resize import fetch_cluster_with_consensus

        cluster = Cluster(PeerList.parse("127.0.0.1:38088"),
                          PeerList.parse("127.0.0.1:26620"))
        srv = ConfigServer(port=0, cluster=cluster).start()
        monkeypatch.setenv("KF_CHAOS_SPEC", "config_down:after=0,count=2")
        _, peers = make_peers(1, 26620, monkeypatch, config_server=srv.url)
        try:
            got, version = fetch_cluster_with_consensus(peers[0], timeout=30)
            assert version == 0 and got.workers == cluster.workers
            ctl = chaos.controller_for(0)
            assert ctl is not None and ctl._fetches == 3  # 2 dark + 1 ok
        finally:
            peers[0].close()
            srv.stop()

    def test_drop_fanout(self, monkeypatch):
        """An injected fan-out loss: the peer detector never hears about
        the failure (the fault the monitored runner must tolerate)."""
        from kungfu_tpu.monitor.detector import DetectorServer

        receiver = DetectorServer(expected_ranks=1, port=27802,
                                  host="127.0.0.2").start()
        sender = DetectorServer(expected_ranks=1, port=27802,
                                host="127.0.0.1",
                                peer_hosts=["127.0.0.2"]).start()
        try:
            monkeypatch.setenv("KF_CHAOS_SPEC", "drop_fanout:host=127.0.0.2")
            sender._fanout({"kind": "otherdown", "epoch": 3})
            time.sleep(0.5)
            assert not receiver.results.down_flag
            # with the fault cleared the same fan-out lands
            monkeypatch.delenv("KF_CHAOS_SPEC")
            chaos.reset()
            sender._fanout({"kind": "otherdown", "epoch": 3})
            deadline = time.time() + 5
            while not receiver.results.down_flag and time.time() < deadline:
                time.sleep(0.1)
            assert receiver.results.down_flag
        finally:
            sender.stop()
            receiver.stop()


class TestTolerantSupervisor:
    """`kfrun -tolerate-failures`: one worker dying must not take the
    group down — the survivors' in-flight shrink needs them alive."""

    def _procs(self):
        import sys

        from kungfu_tpu.runner.proc import Proc

        return [
            Proc(name="dies", prog=sys.executable,
                 args=["-c", "import sys; sys.exit(43)"]),
            Proc(name="survives", prog=sys.executable,
                 args=["-c", "import time; time.sleep(1.5)"]),
        ]

    def test_fail_fast_kills_the_group(self):
        from kungfu_tpu.runner.proc import run_all as proc_run_all

        codes = proc_run_all(self._procs(), quiet=True, timeout=30)
        assert codes[0] == 43
        assert codes[1] != 0  # killed before its natural exit

    def test_tolerant_lets_survivors_finish(self):
        from kungfu_tpu.runner.proc import run_all as proc_run_all

        codes = proc_run_all(self._procs(), quiet=True, timeout=30,
                             fail_fast=False)
        assert codes == [43, 0]


class TestStepSnapshot:
    def test_commit_last_isolation(self):
        snap = StepSnapshot()
        assert snap.last() is None and snap.step() is None
        w = np.arange(4, dtype=np.float32)
        snap.commit(7, {"w": w}, meta={"epoch": 2})
        w[:] = -1  # caller clobbers its buffer post-commit (donation)
        step, tree, meta = snap.last()
        assert step == 7 and meta == {"epoch": 2}
        assert np.array_equal(tree["w"], [0, 1, 2, 3])
        tree["w"][:] = -2  # caller clobbers the restored copy
        _, tree2, _ = snap.last()
        assert np.array_equal(tree2["w"], [0, 1, 2, 3])

    def test_recommit_and_clear(self):
        snap = StepSnapshot()
        snap.commit(1, {"x": np.zeros(2)})
        snap.commit(2, {"x": np.ones(2)})
        assert snap.step() == 2
        snap.clear()
        assert snap.last() is None
