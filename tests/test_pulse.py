"""kf-pulse tests: the GNS/variance estimator math, the PulseMonitor
gating/EMA/gauge contract, the decision ledger (online judging, durable
streams, byte-identical offline replay, closed schema), the monitoring
surfaces that carry the signal (aggregator rollup, kftop PULSE section,
sentinel ``regress:gns``, ``/decisions`` route, ``kfhist --decisions``,
``policy.sentinel_signals``), and THE acceptance chain: a real
``zero_train_step`` loop whose measured ``kf_gns`` flows rank ->
reporter -> aggregator ``/cluster`` -> kftop -> sentinel alert."""

import json
import math
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.monitor import detect, history, kfhist, kftop, timeline
from kungfu_tpu.monitor import ledger as ledgerlib
from kungfu_tpu.monitor import pulse as pulselib
from kungfu_tpu.monitor.aggregator import (
    ClusterAggregator,
    RankReporter,
    field,
    make_snapshot,
)
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.monitor.sentinel import Sentinel, extract_series
from kungfu_tpu.parallel.zero import zero_train_step
from kungfu_tpu.utils import envs

N_DEV = 4

#: every env the pulse/ledger planes key off — these tests must see a
#: clean environment regardless of the invoking shell
_PULSE_ENVS = (
    "KF_PULSE_EVERY", "KF_PULSE_EMA",
    "KF_SENTINEL_DIR", "KF_SENTINEL_WINDOW", "KF_SENTINEL_THRESHOLD",
)


@pytest.fixture(autouse=True)
def _clean_pulse_env(monkeypatch):
    for tok in _PULSE_ENVS:
        monkeypatch.delenv(tok, raising=False)
    ledgerlib.reset()
    yield
    ledgerlib.reset()


def _mesh(tmp_path, **kw):
    """Fake-clock aggregator + attached sentinel (the test_sentinel.py
    idiom): one ingest per logical step, clock bumped 1 s after each."""
    clock = [1000.0]
    agg = ClusterAggregator(stale_after=3600.0, time_fn=lambda: clock[0])
    kw.setdefault("window", 4)
    s = Sentinel(str(tmp_path), period_s=1.0, **kw)
    agg.attach_sentinel(s)
    return agg, s, clock


def _drive(agg, clock, step, step_time_s, **extra):
    agg.ingest(make_snapshot(rank=0, step=step, step_time_s=step_time_s,
                             wall=clock[0], **extra))
    clock[0] += 1.0


# -- the estimator math ------------------------------------------------------
class TestNoiseScale:
    def test_hand_derived_value(self):
        # gl=3, gg=1, b_small=8, n=4 (b_big=32):
        #   |G|^2 = (32*1 - 8*3) / 24 = 1/3
        #   S     = (3 - 1) / (1/8 - 1/32) = 64/3
        #   GNS   = S / |G|^2 = 64
        assert pulselib.noise_scale(3.0, 1.0, 8.0, 4) \
            == pytest.approx(64.0)

    def test_none_below_two_workers(self):
        assert pulselib.noise_scale(3.0, 1.0, 8.0, 1) is None
        assert pulselib.noise_scale(3.0, 1.0, 8.0, 0) is None

    def test_variance_is_clamped_nonnegative(self):
        assert pulselib.grad_variance(3.0, 1.0) == pytest.approx(2.0)
        # float cancellation must not report negative variance
        assert pulselib.grad_variance(1.0, 1.0 + 1e-9) == 0.0


class TestPulseMonitor:
    def test_from_env_disable_and_parse(self, monkeypatch):
        monkeypatch.setenv(pulselib.EVERY_ENV, "0")
        assert pulselib.PulseMonitor.from_env() is None
        monkeypatch.setenv(pulselib.EVERY_ENV, "-3")
        assert pulselib.PulseMonitor.from_env() is None
        monkeypatch.setenv(pulselib.EVERY_ENV, "7")
        assert pulselib.PulseMonitor.from_env().every == 7
        monkeypatch.delenv(pulselib.EVERY_ENV)
        assert pulselib.PulseMonitor.from_env().every \
            == pulselib.DEFAULT_EVERY
        monkeypatch.setenv(pulselib.EVERY_ENV, "bogus")
        assert pulselib.PulseMonitor.from_env().every \
            == pulselib.DEFAULT_EVERY

    def test_counter_gate_first_sample_at_every_th_call(self):
        # step 0 is the compile transient: the counter path must NOT
        # sample the first call, so short runs never pay the
        # instrumented program's compile
        mon = pulselib.PulseMonitor(every=3)
        assert [mon.should_sample() for _ in range(7)] \
            == [False, False, True, False, False, True, False]

    def test_explicit_step_gate_is_modular(self):
        mon = pulselib.PulseMonitor(every=4)
        assert mon.should_sample(step=0)
        assert not any(mon.should_sample(step=i) for i in (1, 2, 3))
        assert mon.should_sample(step=4)
        # explicit steps never advance the internal counter
        assert [mon.should_sample() for _ in range(4)] \
            == [False, False, False, True]

    def test_update_smooths_and_publishes(self):
        mon = pulselib.PulseMonitor(every=1, ema_alpha=0.5)
        out = mon.update(3.0, 1.0, 8.0, 4)
        assert out["gns_raw"] == pytest.approx(64.0)
        assert out["gns"] == pytest.approx(64.0)       # first sample = raw
        assert out["grad_variance_raw"] == pytest.approx(2.0)
        snap = REGISTRY.snapshot()
        assert snap["kf_gns"] == pytest.approx(64.0)
        assert snap["kf_grad_variance"] == pytest.approx(2.0)
        out = mon.update(1.0, 1.0, 8.0, 4)             # raw gns/var = 0
        assert out["gns"] == pytest.approx(32.0)       # 0.5*64 + 0.5*0
        assert out["grad_variance"] == pytest.approx(1.0)
        assert REGISTRY.snapshot()["kf_gns"] == pytest.approx(32.0)
        assert mon.samples == 2

    def test_single_worker_leaves_gns_gauge_untouched(self):
        REGISTRY.gauge("kf_gns").set(123.0)
        mon = pulselib.PulseMonitor(every=1)
        out = mon.update(3.0, 1.0, 8.0, 1)
        assert out["gns"] is None and out["gns_raw"] is None
        # the variance is still defined (and published) on one worker
        assert out["grad_variance"] == pytest.approx(2.0)
        assert REGISTRY.snapshot()["kf_gns"] == pytest.approx(123.0)

    def test_publish_norms_labeled_gauges(self):
        mon = pulselib.PulseMonitor(every=1)
        mon.publish_norms({"moe": 2.5, "dense": 0.5})
        snap = REGISTRY.snapshot()
        assert snap['kf_grad_norm{group="moe"}'] == pytest.approx(2.5)
        assert snap['kf_grad_norm{group="dense"}'] == pytest.approx(0.5)


class TestKnobParity:
    def test_env_tokens_match(self):
        assert envs.PULSE_EVERY == pulselib.EVERY_ENV == "KF_PULSE_EVERY"
        assert envs.PULSE_EMA == pulselib.EMA_ENV == "KF_PULSE_EMA"

    def test_defaults_match(self):
        kb = envs.pulse_knobs()
        assert kb["every"] == pulselib.DEFAULT_EVERY
        assert kb["ema"] == pulselib.DEFAULT_EMA_ALPHA

    def test_env_overrides_flow_both_sides(self, monkeypatch):
        monkeypatch.setenv(envs.PULSE_EVERY, "5")
        monkeypatch.setenv(envs.PULSE_EMA, "0.5")
        assert envs.pulse_knobs() == {"every": 5, "ema": 0.5}
        mon = pulselib.PulseMonitor.from_env()
        assert mon.every == 5 and mon.ema_alpha == 0.5


# -- the decision ledger -----------------------------------------------------
class TestLedgerSchema:
    def test_unknown_write_field_raises(self):
        with pytest.raises(ValueError, match="bogus"):
            ledgerlib.ledger_record(kind="decision", bogus=1)

    def test_unknown_read_field_raises(self):
        with pytest.raises(KeyError, match="bogus"):
            ledgerlib.lfield({}, "bogus")

    def test_read_tolerates_non_dict(self):
        assert ledgerlib.lfield(None, "actor", "dflt") == "dflt"


class TestDecisionLedger:
    def _feed(self, led, values, series="step_time_s"):
        out = []
        for v in values:
            out.extend(led.on_sample({"series": {series: v}}))
        return out

    def test_improved_verdict_and_join(self, tmp_path):
        led = ledgerlib.DecisionLedger(str(tmp_path), window=3,
                                       threshold=4.0)
        self._feed(led, [1.0, 1.01, 0.99])
        rec = led.decide("bandit-host", "strategy", "STAR", "MST",
                         consensus_seq=7)
        assert ledgerlib.lfield(rec, "seq") == 1
        assert ledgerlib.lfield(rec, "series_n") == 3
        effects = self._feed(led, [0.1, 0.12, 0.11])
        assert len(effects) == 1
        e = effects[0]
        assert ledgerlib.lfield(e, "verdict") == "improved"
        assert ledgerlib.lfield(e, "decision_seq") == 1
        assert ledgerlib.lfield(e, "before_median") == pytest.approx(1.0)
        assert ledgerlib.lfield(e, "after_median") == pytest.approx(0.11)
        summ = led.summary()
        assert summ["total"] == 1 and summ["judged"] == 1
        assert summ["pending"] == 0
        assert summ["by_verdict"] == {"improved": 1}
        view = led.view()
        assert view["kfledger"] == 1
        row = view["decisions"][0]
        assert ledgerlib.lfield(row["decision"], "actor") == "bandit-host"
        assert ledgerlib.lfield(row["effect"], "verdict") == "improved"

    def test_regressed_and_neutral_verdicts(self, tmp_path):
        led = ledgerlib.DecisionLedger(str(tmp_path), window=3,
                                       threshold=4.0)
        self._feed(led, [1.0, 1.01, 0.99])
        led.decide("a", "k", 1, 2)
        (e,) = self._feed(led, [5.0, 5.1, 5.05])
        assert ledgerlib.lfield(e, "verdict") == "regressed"
        self._feed(led, [5.0] * 3)
        led.decide("a", "k", 2, 3)
        (e,) = self._feed(led, [5.0, 5.05, 5.02])
        assert ledgerlib.lfield(e, "verdict") == "neutral"

    def test_good_direction_up_flips_the_sign(self, tmp_path):
        led = ledgerlib.DecisionLedger(str(tmp_path), window=3,
                                       threshold=4.0)
        self._feed(led, [1.0, 1.01, 0.99], series="mfu")
        led.decide("scaler", "replicas", 4, 8, effect_series="mfu",
                   good_direction="up")
        (e,) = self._feed(led, [5.0, 5.1, 5.05], series="mfu")
        assert ledgerlib.lfield(e, "verdict") == "improved"

    def test_insufficient_without_baseline(self, tmp_path):
        led = ledgerlib.DecisionLedger(str(tmp_path), window=3)
        led.decide("a", "k", 1, 2)          # no BEFORE samples at all
        effects = self._feed(led, [0.1, 0.1, 0.1])
        assert [ledgerlib.lfield(e, "verdict") for e in effects] \
            == ["insufficient"]
        assert ledgerlib.lfield(effects[0], "before_median") is None

    def test_pending_until_after_window_fills(self, tmp_path):
        led = ledgerlib.DecisionLedger(str(tmp_path), window=3)
        self._feed(led, [1.0] * 3)
        led.decide("a", "k", 1, 2)
        assert self._feed(led, [0.1, 0.1]) == []
        assert led.summary()["pending"] == 1
        assert len(self._feed(led, [0.1])) == 1

    def test_judge_math_matches_detect_floors(self):
        d = ledgerlib.ledger_record(
            kfledger=1, kind="decision", seq=9, actor="a", knob="k",
            window=4, threshold=4.0, effect_series="step_time_s",
            good_direction="down")
        before = [1.0, 1.1, 0.9, 1.0]
        after = [0.5, 0.55, 0.45, 0.5]
        e = ledgerlib.judge(d, before, after)
        med = detect.median(before)
        scale = max(detect.mad(before, med),
                    detect.DEFAULT_REL_FLOOR * abs(med) / 4.0,
                    detect.ABS_FLOOR)
        want = (detect.median(after) - med) / scale
        assert ledgerlib.lfield(e, "score") == round(want, 6)
        assert ledgerlib.lfield(e, "verdict") == "improved"

    def test_decision_ticks_counter_and_timeline(self, tmp_path):
        before = REGISTRY.counter("kf_decisions_total",
                                  actor="test-actor").value
        led = ledgerlib.DecisionLedger(str(tmp_path), window=2)
        cursor, _ = timeline.events_tail(0)
        led.decide("test-actor", "k", 1, 2)
        after = REGISTRY.counter("kf_decisions_total",
                                 actor="test-actor").value
        assert after == before + 1
        # force=True: the mark lands in the ring even with tracing off
        _, events = timeline.events_tail(cursor)
        marks = [e for e in events if e.get("kind") == "decision"]
        assert marks and marks[-1]["name"] == "test-actor"


class TestRecordDecisionHook:
    def test_inactive_without_sentinel_dir(self):
        assert ledgerlib.active() is None
        assert ledgerlib.record_decision("a", "k", 1, 2) is None

    def test_active_routes_to_env_keyed_singleton(self, monkeypatch,
                                                  tmp_path):
        monkeypatch.setenv("KF_SENTINEL_DIR", str(tmp_path))
        monkeypatch.setenv("KF_SENTINEL_WINDOW", "3")
        rec = ledgerlib.record_decision("bandit-host", "strategy",
                                        "STAR", "MST")
        assert ledgerlib.lfield(rec, "actor") == "bandit-host"
        led = ledgerlib.active()
        assert led is ledgerlib.ledger_for(str(tmp_path))
        assert led.window == 3
        records, skipped = history.scan_stream(
            str(tmp_path), ledgerlib.DECISIONS_STREAM)
        assert skipped == 0 and len(records) == 1
        assert records[0]["kind"] == "decision"

    def test_never_raises_through_actor(self, monkeypatch, tmp_path):
        monkeypatch.setenv("KF_SENTINEL_DIR", str(tmp_path))

        def boom(self, *a, **kw):
            raise RuntimeError("unwritable ledger")

        monkeypatch.setattr(ledgerlib.DecisionLedger, "decide", boom)
        assert ledgerlib.record_decision("a", "k", 1, 2) is None


class TestOfflineReplay:
    def _run(self, root, window=3):
        """Durable online run: cluster stream + ledger fed the EXACT
        same records (the sentinel's _observe_locked contract)."""
        led = ledgerlib.ledger_for(root, window=window)
        ring = history.HistoryRing(root, "cluster")

        def feed(v):
            rec = {"series": {"step_time_s": v}}
            ring.append(rec)
            led.on_sample(rec)

        for v in [1.0, 1.02, 0.98]:
            feed(v)
        led.decide("bandit-host", "strategy", "STAR", "MST")
        for v in [0.1, 0.12, 0.11]:
            feed(v)
        led.decide("bandit-host", "strategy", "MST", "RING")
        for v in [0.1, 0.11]:
            feed(v)                         # second decision stays pending
        return led

    def test_replay_is_byte_identical(self, tmp_path):
        self._run(str(tmp_path))
        out = ledgerlib.replay_effects(str(tmp_path))
        judged = [r for r in out["decisions"] if r["online"] is not None]
        assert len(judged) == 1
        for row in judged:
            assert json.dumps(row["online"], sort_keys=True) \
                == json.dumps(row["replayed"], sort_keys=True)

    def test_kfhist_decisions_flags_matches(self, tmp_path):
        self._run(str(tmp_path))
        out = kfhist.decisions_from_dir(str(tmp_path))
        matches = [r["match"] for r in out["decisions"]]
        assert matches == [True, None]      # judged + still pending

    def test_kfhist_cli_decisions_json(self, tmp_path, capsys):
        self._run(str(tmp_path))
        rc = kfhist.main(["--dir", str(tmp_path), "--decisions", "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["kfledger"] == 1
        assert payload["decisions"][0]["match"] is True


# -- monitoring surfaces -----------------------------------------------------
class TestAggregatorPulse:
    def _instrumented(self):
        agg = ClusterAggregator(stale_after=3600.0)
        agg.ingest(make_snapshot(
            rank=0, step=3, step_time_s=0.1,
            gauges={"kf_gns": 4.0, "kf_grad_variance": 1.0,
                    'kf_grad_norm{group="dense"}': 2.0}))
        agg.ingest(make_snapshot(
            rank=1, step=3, step_time_s=0.1,
            gauges={"kf_gns": 6.0, "kf_grad_variance": 3.0,
                    'kf_grad_norm{group="dense"}': 4.0}))
        return agg

    def test_cluster_rollup_means(self):
        view = self._instrumented().cluster_view()
        pl = field(view, "pulse")
        assert pl["gns"] == pytest.approx(5.0)
        assert pl["grad_variance"] == pytest.approx(2.0)
        assert pl["groups"] == {"dense": pytest.approx(3.0)}

    def test_prometheus_gauges(self):
        prom = self._instrumented().render_prometheus()
        assert "kf_cluster_gns 5" in prom
        assert "kf_cluster_grad_variance 2" in prom

    def test_absent_when_uninstrumented(self):
        agg = ClusterAggregator(stale_after=3600.0)
        agg.ingest(make_snapshot(rank=0, step=3, step_time_s=0.1))
        view = agg.cluster_view()
        assert field(view, "pulse") is None
        assert "== PULSE" not in kftop.render_view(view)
        assert "kf_cluster_gns" not in agg.render_prometheus()

    def test_kftop_pulse_section(self):
        text = kftop.render_view(self._instrumented().cluster_view())
        assert "== PULSE" in text
        assert "gns 5" in text
        assert "per-rank gns: r0:4 r1:6" in text

    def test_kftop_decisions_line(self, tmp_path):
        agg, s, clock = _mesh(tmp_path)
        s.ledger.decide("bandit-host", "strategy", "STAR", "MST")
        _drive(agg, clock, 0, 0.1)
        text = kftop.render_view(agg.cluster_view())
        assert "decisions: 1 made" in text


class TestSentinelGns:
    def test_extract_series_gns_rollup(self):
        view = {"ranks": [
            {"rank": 0, "step": 5,
             "gauges": {"kf_gns": 4.0, "kf_grad_variance": 0.5}},
            {"rank": 1, "step": 5, "gauges": {"kf_gns": 6.0}},
        ]}
        s = extract_series(view)
        assert s["gns"] == pytest.approx(5.0)
        assert s["grad_variance"] == pytest.approx(0.5)
        assert "gns" not in extract_series(
            {"ranks": [{"rank": 0, "step": 5}]})

    def test_planted_gns_shift_fires_regress(self, tmp_path):
        agg, s, clock = _mesh(tmp_path)
        for i in range(16):
            _drive(agg, clock, i, 0.1, gauges={"kf_gns": 5.0})
        assert s.alerts_view()["alerts"] == []
        fired_after = None
        for j in range(16):
            _drive(agg, clock, 16 + j, 0.1, gauges={"kf_gns": 25.0})
            if any(a["rule"] == "regress:gns"
                   for a in s.alerts_view()["alerts"]):
                fired_after = j + 1
                break
        assert fired_after is not None and fired_after <= 2 * s.window

    def test_gns_direction_is_up_only(self, tmp_path):
        # DIRECTIONS pins gns "up": a drop (more data-parallel headroom)
        # is not a regression
        agg, s, clock = _mesh(tmp_path)
        for i in range(16):
            _drive(agg, clock, i, 0.1, gauges={"kf_gns": 25.0})
        for j in range(16):
            _drive(agg, clock, 16 + j, 0.1, gauges={"kf_gns": 5.0})
        assert "regress:gns" not in s.alerts_view()["active"]


class TestPolicySignals:
    def test_decisions_shape_in_signals(self, tmp_path):
        from kungfu_tpu.policy.sentinel import sentinel_signals

        agg, s, clock = _mesh(tmp_path)
        s.ledger.decide("bandit-host", "strategy", "STAR", "MST")
        _drive(agg, clock, 0, 0.1)
        sig = sentinel_signals(s.alerts_view())
        assert sig is not None
        dec = sig["decisions"]
        assert dec["total"] == 1 and dec["pending"] == 1
        assert set(dec) >= {"total", "judged", "pending", "by_verdict",
                            "last"}


class TestDecisionsRoute:
    @pytest.fixture
    def server(self):
        from kungfu_tpu.elastic.configserver import ConfigServer
        from kungfu_tpu.plan import Cluster, PeerList

        workers = PeerList.parse(
            "127.0.0.1:27461,127.0.0.1:27462,127.0.0.1:27463")
        cluster = Cluster(PeerList.parse("127.0.0.1:38094"), workers)
        agg = ClusterAggregator(stale_after=60.0)
        srv = ConfigServer(port=0, cluster=cluster, aggregator=agg).start()
        yield srv, agg, f"http://127.0.0.1:{srv.port}"
        srv.stop()

    def test_404_then_ledger_view(self, server, tmp_path):
        srv, agg, base = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/decisions", timeout=5)
        assert ei.value.code == 404
        s = Sentinel(str(tmp_path), window=4)
        agg.attach_sentinel(s)
        s.ledger.decide("bandit-host", "strategy", "STAR", "MST")
        with urllib.request.urlopen(base + "/decisions", timeout=5) as resp:
            payload = json.loads(resp.read().decode())
        assert payload["kfledger"] == 1
        assert payload["summary"]["total"] == 1
        d = payload["decisions"][0]["decision"]
        assert d["actor"] == "bandit-host" and d["new"] == "MST"


# -- the acceptance chain ----------------------------------------------------
def _mlp_arms(monkeypatch):
    """Two zero stage-2 builds from identical init: KF_PULSE_EVERY=0
    (bare) and =2 (instrumented)."""
    comm = Communicator(devices=jax.devices()[:N_DEV], local_size=N_DEV)
    rng = np.random.RandomState(0)
    params = {"w0": jnp.asarray(rng.randn(12, 6), jnp.float32),
              "w1": jnp.asarray(rng.randn(6, 3), jnp.float32)}
    batch = (jnp.asarray(rng.randn(4 * N_DEV, 12), jnp.float32),
             jnp.asarray(rng.randn(4 * N_DEV, 3), jnp.float32))

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((jnp.tanh(x @ p["w0"]) @ p["w1"] - y) ** 2)

    arms = {}
    for name, every in (("bare", "0"), ("pulse", "2")):
        monkeypatch.setenv("KF_PULSE_EVERY", every)
        z = zero_train_step(loss_fn, optax.adam(1e-2), comm, stage=2)
        arms[name] = [z, z.init_params(params), z.init_opt(params)]
    return arms, batch


class TestZeroPulseEndToEnd:
    def test_kf_gns_full_chain(self, monkeypatch, tmp_path):
        """ISSUE 20 acceptance: a real zero_train_step loop measures
        kf_gns; the gauge rides the rank snapshot to a live aggregator's
        /cluster view, renders in kftop's PULSE section, and a planted
        shift of the measured value trips the sentinel's regress:gns."""
        arms, batch = _mlp_arms(monkeypatch)
        (z_off, p_off, o_off) = arms["bare"]
        (z_on, p_on, o_on) = arms["pulse"]
        assert z_off.pulse is None and z_on.pulse is not None

        for _ in range(4):
            p_off, o_off, _ = z_off.step(p_off, o_off, batch)
            p_on, o_on, _ = z_on.step(p_on, o_on, batch)
        jax.block_until_ready((p_off, p_on))
        # counter gate: samples at calls 2 and 4
        assert z_on.pulse.samples == 2
        # off steps run the bare program untouched — bitwise equal
        for k in p_off:
            assert np.array_equal(np.asarray(p_off[k]),
                                  np.asarray(p_on[k])), k
        gns = REGISTRY.snapshot().get("kf_gns")
        assert gns is not None and math.isfinite(float(gns))
        gns = float(gns)

        # rank -> reporter -> live aggregator -> /cluster -> kftop
        from kungfu_tpu.elastic.configserver import ConfigServer
        from kungfu_tpu.plan import Cluster, PeerList

        cluster = Cluster(PeerList.parse("127.0.0.1:38095"),
                          PeerList.parse("127.0.0.1:27471"))
        agg = ClusterAggregator(stale_after=60.0)
        srv = ConfigServer(port=0, cluster=cluster,
                           aggregator=agg).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            RankReporter(0, base + "/get", period=30.0).push_once()
            with urllib.request.urlopen(base + "/cluster",
                                        timeout=5) as resp:
                view = json.loads(resp.read().decode())
        finally:
            srv.stop()
        pl = field(view, "pulse")
        assert pl is not None
        assert pl["gns"] == pytest.approx(gns)
        text = kftop.render_view(view)
        assert "== PULSE" in text and "r0:" in text

        # sentinel: the measured value is the baseline; a planted 5x
        # shift must fire regress:gns
        agg2, s, clock = _mesh(tmp_path)
        for i in range(16):
            _drive(agg2, clock, i, 0.1, gauges={"kf_gns": gns})
        assert "regress:gns" not in s.alerts_view()["active"]
        for j in range(16):
            _drive(agg2, clock, 16 + j, 0.1,
                   gauges={"kf_gns": gns * 5.0})
            if "regress:gns" in s.alerts_view()["active"]:
                break
        assert "regress:gns" in s.alerts_view()["active"]

    def test_dp_train_step_pulse(self, monkeypatch):
        """The dp path: same monitor, same gauges, pulse attr exposed."""
        from kungfu_tpu.optimizers import synchronous_sgd
        from kungfu_tpu.parallel.train import dp_train_step

        comm = Communicator(devices=jax.devices()[:N_DEV],
                            local_size=N_DEV)
        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
        batch = (jnp.asarray(rng.randn(2 * N_DEV, 8), jnp.float32),
                 jnp.asarray(rng.randn(2 * N_DEV, 4), jnp.float32))

        def loss_fn(p, b):
            x, y = b
            return jnp.mean((x @ p["w"] - y) ** 2)

        monkeypatch.setenv("KF_PULSE_EVERY", "1")
        tx = synchronous_sgd(optax.sgd(0.1), comm.axis)
        step = dp_train_step(loss_fn, tx, comm)
        assert step.pulse is not None and step.pulse.every == 1
        p, o = params, tx.init(params)
        p, o, loss = step(p, o, batch)
        jax.block_until_ready(loss)
        assert step.pulse.samples == 1
        gns = REGISTRY.snapshot().get("kf_gns")
        assert gns is not None and math.isfinite(float(gns))


@pytest.mark.slow  # compile-heavy: a second ShardedTrainer jit program
class TestShardedTrainerPulse:
    def test_mixed_mesh_publishes_norms_only(self, monkeypatch):
        """tp/sp sharding makes the two-batch GNS pair undefined — the
        trainer must publish per-kind norms and leave kf_gns alone."""
        from kungfu_tpu.models.transformer import TransformerConfig
        from kungfu_tpu.parallel import MeshPlan, ShardedTrainer

        monkeypatch.setenv("KF_PULSE_EVERY", "1")
        REGISTRY.gauge("kf_gns").set(-7.0)  # sentinel value
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq=32, causal=True, pos="rope", dtype="float32")
        trainer = ShardedTrainer(cfg, MeshPlan(dp=2, pp=1, sp=1, tp=2))
        assert trainer.pulse is not None
        from kungfu_tpu.models.transformer import Transformer

        params = trainer.from_transformer_params(
            Transformer(cfg).init(jax.random.PRNGKey(0)))
        state = {"params": params, "opt_state": trainer.tx.init(params),
                 "step": 0}
        rng = np.random.default_rng(0)
        batch = (jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32),
                 jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32))
        state, loss = trainer.step(state, batch)
        assert np.isfinite(float(loss))
        assert trainer.pulse.samples == 0      # GNS pair undefined here
        snap = REGISTRY.snapshot()
        norm_keys = [k for k in snap if k.startswith('kf_grad_norm{')]
        assert norm_keys and all(math.isfinite(snap[k])
                                 for k in norm_keys)
        assert snap["kf_gns"] == pytest.approx(-7.0)   # untouched

    def test_pure_dp_mesh_measures_gns(self, monkeypatch):
        from kungfu_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )
        from kungfu_tpu.parallel import MeshPlan, ShardedTrainer

        monkeypatch.setenv("KF_PULSE_EVERY", "1")
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq=32, causal=True, pos="rope", dtype="float32")
        trainer = ShardedTrainer(cfg, MeshPlan(dp=4, pp=1, sp=1, tp=1))
        params = trainer.from_transformer_params(
            Transformer(cfg).init(jax.random.PRNGKey(0)))
        state = {"params": params, "opt_state": trainer.tx.init(params),
                 "step": 0}
        rng = np.random.default_rng(1)
        batch = (jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32),
                 jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32))
        state, loss = trainer.step(state, batch)
        assert np.isfinite(float(loss))
        assert trainer.pulse.samples == 1
        gns = REGISTRY.snapshot().get("kf_gns")
        assert gns is not None and math.isfinite(float(gns))
