"""Live device-plane elasticity over a provisioned world.

Round-3 VERDICT item 1: surviving workers must join the new device world
after a resize WITHOUT process relaunch (the reference's live resize,
``peer/peer.go:236-276`` + ``gpu/scheduler.cpp:43-72``).  The TPU design:
``KF_WORLD_PEERS`` provisions a max world, the jax.distributed world is
booted once over ALL slots, and each mesh epoch is a sub-mesh carved over
the *active* workers' devices (``Peer._carve_active_devices``).

The integration test runs the reference-shaped proof: a 4-slot world with
a 2→4→2 schedule, each active worker running a device-plane (gloo CPU
backend, NOT host-plane) allreduce every epoch.  Asserts:

* the psum spans exactly the active set in every epoch;
* worker 0's PID never changes (survivor keeps training in-process);
* dropped workers go standby and exit cleanly at the shutdown sentinel;
* the fixed-world "stale device world" warning path never fires.
"""

import glob
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestWorldEnvContract:
    def test_job_world_envs(self):
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.runner.job import Job
        from kungfu_tpu.utils import envs as E

        hl = HostList.parse("127.0.0.1:4")
        world = hl.gen_peer_list(4)
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(2))
        job = Job(prog="python3", args=["t.py"], backend="cpu", world=world)
        procs = job.create_procs(cluster, "127.0.0.1")
        # device-world mode spawns ALL provisioned slots, not just actives
        assert len(procs) == 4
        for i, p in enumerate(procs):
            assert p.envs[E.WORLD_PEERS] == str(world)
            assert p.envs[E.NUM_PROCESSES] == "4"
            assert p.envs[E.PROCESS_ID] == str(i)
            assert E.COORDINATOR in p.envs
            assert p.envs[E.NUM_DEVICES] == "1"

    def test_config_parses_world(self):
        from kungfu_tpu.utils import envs as E

        env = {
            E.SELF_SPEC: "127.0.0.1:10002",
            E.INIT_PEERS: "127.0.0.1:10000,127.0.0.1:10001",
            E.WORLD_PEERS: ",".join(f"127.0.0.1:{10000 + i}" for i in range(4)),
        }
        cfg = E.parse_config_from_env(env)
        assert cfg.world_peers is not None and len(cfg.world_peers) == 4
        # process identity = stable world-slot index, not elastic rank
        assert cfg.process_id == 2
        assert cfg.num_processes == 4
        assert cfg.detached  # not in the initial worker list...

    def test_world_requires_membership(self):
        from kungfu_tpu.utils import envs as E

        env = {
            E.SELF_SPEC: "127.0.0.1:20000",
            E.INIT_PEERS: "127.0.0.1:10000",
            E.WORLD_PEERS: "127.0.0.1:10000,127.0.0.1:10001",
        }
        with pytest.raises(ValueError):
            E.parse_config_from_env(env)

    def test_standby_flag_and_no_communicator(self):
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils import envs as E

        env = {
            E.SELF_SPEC: "127.0.0.1:10003",
            E.INIT_PEERS: "127.0.0.1:10000,127.0.0.1:10001",
            E.WORLD_PEERS: ",".join(f"127.0.0.1:{10000 + i}" for i in range(4)),
        }
        peer = Peer(config=E.parse_config_from_env(env))
        assert peer.standby
        with pytest.raises(RuntimeError, match="standby"):
            peer.communicator()

    def test_watch_keeps_standby_alive(self):
        """Device-world watch runner must not kill in-world workers on
        shrink (they transition to standby themselves)."""
        from kungfu_tpu.plan import Cluster, HostList

        hl = HostList.parse("127.0.0.1:4")
        world = hl.gen_peer_list(4)
        big = Cluster(hl.gen_runner_list(), hl.gen_peer_list(4))
        small = Cluster(hl.gen_runner_list(), hl.gen_peer_list(2))
        old_local = set(big.workers.on_host("127.0.0.1"))
        new_local = set(small.workers.on_host("127.0.0.1"))
        world_local = set(world.on_host("127.0.0.1"))
        removed = (old_local - new_local) - world_local
        added = (new_local - old_local) - world_local
        assert removed == set() and added == set()


@pytest.mark.slow
class TestLiveResize:
    def test_2_4_2_schedule_device_plane(self, tmp_path):
        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:4", "-w", "-device-world",
             "-builtin-config-port", "9311", "-logdir", logdir, "-q",
             sys.executable, "examples/device_elastic.py",
             "--", "--schedule", "2,4,2"],
            cwd=REPO, capture_output=True, text=True, timeout=300, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr

        lines = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            with open(f) as fh:
                lines += fh.read().splitlines()
        epochs = {}
        for ln in lines:
            m = re.match(
                r"KFEPOCH v=(\d+) size=(\d+) rank=(\d+) world_rank=(\d+) "
                r"psum=([\d.]+) expect=([\d.]+) pid=(\d+) ok=(\w+)", ln)
            if m:
                v = int(m.group(1))
                epochs.setdefault(v, []).append(
                    dict(size=int(m.group(2)), rank=int(m.group(3)),
                         world_rank=int(m.group(4)), psum=float(m.group(5)),
                         expect=float(m.group(6)), pid=int(m.group(7)),
                         ok=m.group(8) == "True"))
        # every epoch ran on the device plane with the psum spanning
        # EXACTLY the active set: 2 workers -> 1+2=3, 4 workers -> 10
        assert sorted(epochs) == [0, 1, 2], lines
        assert [e["psum"] for e in epochs[0]] == [3.0, 3.0]
        assert len(epochs[1]) == 4
        assert all(e["psum"] == 10.0 for e in epochs[1])
        assert [e["psum"] for e in epochs[2]] == [3.0, 3.0]
        assert all(e["ok"] for v in epochs.values() for e in v)

        # worker 0 survived all three epochs in ONE process
        w0_pids = {e["pid"] for v in epochs.values() for e in v
                   if e["world_rank"] == 0}
        assert len(w0_pids) == 1
        # slots 2 and 3 were standby, joined live at epoch 1 only, and
        # exited cleanly (KFDONE) rather than being killed
        done = {int(m.group(1)) for ln in lines
                if (m := re.match(r"KFDONE world_rank=(\d+)", ln))}
        assert done == {0, 1, 2, 3}
        for wr in (2, 3):
            its = [v for v, es in epochs.items()
                   for e in es if e["world_rank"] == wr]
            assert its == [1]

        # the fixed-world stale-device-world warning path must be
        # unreachable under a provisioned world
        stderr_all = ""
        for f in glob.glob(os.path.join(logdir, "*.stderr.log")):
            with open(f) as fh:
                stderr_all += fh.read()
        assert "keep their original device world" not in stderr_all

    def test_strategy_survives_mesh_epochs_e2e(self, tmp_path):
        """An allreduce schedule installed on epoch 0 must be the active
        strategy on every later mesh epoch of every worker — including
        joiners that were standby at install time and the post-shrink
        epoch (the real _propose/rejoin paths, not the unit-test
        shortcut)."""
        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:4", "-w", "-device-world",
             "-builtin-config-port", "9313", "-logdir", logdir, "-q",
             sys.executable, "examples/device_elastic.py",
             "--", "--schedule", "2,4,2", "--strategy", "ring"],
            cwd=REPO, capture_output=True, text=True, timeout=420, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        lines = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            with open(f) as fh:
                lines += fh.read().splitlines()
        seen = {}
        for ln in lines:
            m = re.match(
                r"KFEPOCH v=(\d+) .*world_rank=(\d+) .*ok=(\w+) "
                r"strategy=(\w+)", ln)
            if m:
                seen.setdefault(int(m.group(1)), []).append(
                    (int(m.group(2)), m.group(3), m.group(4)))
        assert sorted(seen) == [0, 1, 2], lines
        for v, rows in seen.items():
            for world_rank, ok, strategy in rows:
                assert ok == "True", (v, rows)
                # EVERY member of every epoch — survivors across the
                # shrink AND the standby joiners at v=1 — must run rank
                # 0's installed schedule: a mixed-schedule mesh would be
                # two different compiled programs on one collective
                assert strategy == "ring", (v, world_rank, rows)

    def test_zero1_training_survives_mesh_epochs(self, tmp_path):
        """ZeRO-1 across live resizes: the 1/n-sharded optimizer state is
        snapshot/restored over the host plane at each epoch boundary —
        every member of every epoch must report the bit-identical loss
        (replicas in sync through two re-chunkings)."""
        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:4", "-w", "-device-world",
             "-builtin-config-port", "9315", "-logdir", logdir, "-q",
             sys.executable, "examples/device_elastic.py",
             "--", "--schedule", "2,4,2", "--train", "--zero1"],
            cwd=REPO, capture_output=True, text=True, timeout=420, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        lines = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            with open(f) as fh:
                lines += fh.read().splitlines()
        losses = {}
        for ln in lines:
            m = re.match(r"KFEPOCH v=(\d+) .*ok=True.* loss=([\d.eE+-]+)", ln)
            if m:
                losses.setdefault(int(m.group(1)), []).append(m.group(2))
        assert sorted(losses) == [0, 1, 2], lines
        assert [len(losses[v]) for v in (0, 1, 2)] == [2, 4, 2]
        for v, vals in losses.items():
            assert len(set(vals)) == 1, f"epoch {v} replicas diverged: {vals}"
        # the sharded state carried: losses are all distinct epoch to
        # epoch and the run keeps improving on the repeated batches
        l0, l1, l2 = (float(losses[v][0]) for v in (0, 1, 2))
        assert len({l0, l1, l2}) == 3 and l2 < l0, (l0, l1, l2)

    def test_autotune_agrees_on_multiprocess_mesh(self, tmp_path):
        """Round-3 VERDICT weak #8: autotune on a multi-controller mesh
        must ride the settled chained-K harness (no eager fallback) and
        every process must install the SAME measured winner."""
        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:2", "-w", "-device-world",
             "-builtin-config-port", "9314", "-logdir", logdir, "-q",
             sys.executable, "examples/device_elastic.py",
             "--", "--schedule", "2", "--autotune"],
            cwd=REPO, capture_output=True, text=True, timeout=300, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        lines = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            with open(f) as fh:
                lines += fh.read().splitlines()
        winners = [m.group(1) for ln in lines
                   if (m := re.search(r"ok=True strategy=(\w+)", ln))]
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES
        assert len(winners) == 2, lines
        assert len(set(winners)) == 1, winners
        assert winners[0] in ALLREDUCE_SCHEDULES

    def test_training_survives_mesh_epochs(self, tmp_path):
        """REAL S-SGD training (dp_train_step over the re-carved
        Communicator) across 2→4→2: every member of an epoch must report
        the bit-identical loss (replicas in sync — joiners adopted the
        survivors' weights, psummed grads kept them identical)."""
        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:4", "-w", "-device-world",
             "-builtin-config-port", "9312", "-logdir", logdir, "-q",
             sys.executable, "examples/device_elastic.py",
             "--", "--schedule", "2,4,2", "--train", "--resync-root", "1"],
            cwd=REPO, capture_output=True, text=True, timeout=420, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        lines = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            with open(f) as fh:
                lines += fh.read().splitlines()
        losses = {}
        for ln in lines:
            m = re.match(r"KFEPOCH v=(\d+) .*ok=True.* loss=([\d.eE+-]+)", ln)
            if m:
                losses.setdefault(int(m.group(1)), []).append(m.group(2))
        assert sorted(losses) == [0, 1, 2], lines
        assert [len(losses[v]) for v in (0, 1, 2)] == [2, 4, 2]
        for v, vals in losses.items():
            assert len(set(vals)) == 1, f"epoch {v} replicas diverged: {vals}"
        # the weights CARRIED across epochs: every epoch replays the same
        # batch sequence (fixed data seed), so a silent re-init would
        # repeat epoch 0's loss bit-for-bit, and continued training on
        # repeated data must keep improving
        l0, l1, l2 = (float(losses[v][0]) for v in (0, 1, 2))
        assert len({l0, l1, l2}) == 3, (l0, l1, l2)
        assert l2 < l0, (l0, l2)
