"""Elastic dataset adaptor tests — parity with the reference's dataset
adaptor integration test (tests/python/integration, datasets/adaptor.py)."""

import numpy as np
import pytest

from kungfu_tpu.datasets import ElasticDataset


def collect(ds, n):
    return [ds.next_batch() for _ in range(n)]


class TestSharding:
    def test_disjoint_and_complete_cover(self):
        x = np.arange(64)
        seen = []
        for rank in range(4):
            ds = ElasticDataset([x], batch_size=4, rank=rank, size=4, seed=1)
            for (b,) in collect(ds, ds.batches_per_epoch()):
                seen.extend(b.tolist())
        assert sorted(seen) == list(range(64))

    def test_ranks_agree_on_permutation(self):
        x = np.arange(32)
        d0 = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=9)
        d1 = ElasticDataset([x], batch_size=4, rank=1, size=2, seed=9)
        (b0,) = d0.next_batch()
        (b1,) = d1.next_batch()
        assert set(b0) & set(b1) == set()

    def test_multiple_arrays_stay_aligned(self):
        x = np.arange(40)
        y = np.arange(40) * 10
        ds = ElasticDataset([x, y], batch_size=5, seed=3)
        bx, by = ds.next_batch()
        np.testing.assert_array_equal(by, bx * 10)

    def test_short_tail_dropped(self):
        ds = ElasticDataset([np.arange(10)], batch_size=3, rank=0, size=1)
        assert ds.batches_per_epoch() == 3

    def test_too_small_raises(self):
        ds = ElasticDataset([np.arange(3)], batch_size=4)
        with pytest.raises(ValueError):
            ds.next_batch()


class TestElasticResume:
    def test_resize_continues_stream(self):
        """Grow 1→2 mid-epoch: the union of what both shapes consumed has
        no overlap with what the old shape consumed after the boundary."""
        x = np.arange(64)
        ds = ElasticDataset([x], batch_size=4, rank=0, size=1, seed=5)
        first = [ds.next_batch()[0] for _ in range(4)]  # 16 samples at np=1
        consumed = ds.consumed
        # resize to 2 workers; both resume from the same global offset
        a = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=5)
        b = ElasticDataset([x], batch_size=4, rank=1, size=2, seed=5)
        a.skip(consumed)
        b.skip(consumed)
        nxt = np.concatenate([a.next_batch()[0], b.next_batch()[0]])
        already = np.concatenate(first)
        assert set(nxt) & set(already) == set()

    def test_skip_resumes_exactly(self):
        x = np.arange(48)
        ds = ElasticDataset([x], batch_size=4, seed=2)
        collect(ds, 3)
        mark = ds.consumed
        (expected,) = ds.next_batch()
        ds2 = ElasticDataset([x], batch_size=4, seed=2)
        ds2.skip(mark)
        (got,) = ds2.next_batch()
        np.testing.assert_array_equal(got, expected)

    def test_unaligned_skip_rounds_up(self):
        x = np.arange(64)
        ds = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=0)
        ds.skip(13)  # global batch is 8 → realigns to 16
        ds.next_batch()
        assert ds.consumed == 24

    def test_epoch_reshuffles(self):
        x = np.arange(16)
        ds = ElasticDataset([x], batch_size=16, seed=4)
        (e0,) = ds.next_batch()
        (e1,) = ds.next_batch()
        assert not np.array_equal(e0, e1)
        assert sorted(e0) == sorted(e1)

    def test_epochs_iterator(self):
        x = np.arange(24)
        ds = ElasticDataset([x], batch_size=6, seed=0)
        batches = list(ds.epochs(2))
        assert len(batches) == 8  # 4 per epoch x 2

    def test_no_shuffle_identity_order(self):
        x = np.arange(12)
        ds = ElasticDataset([x], batch_size=4, shuffle=False)
        np.testing.assert_array_equal(ds.next_batch()[0], [0, 1, 2, 3])
