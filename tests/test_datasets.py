"""Elastic dataset adaptor tests — parity with the reference's dataset
adaptor integration test (tests/python/integration, datasets/adaptor.py)."""

import os

import numpy as np
import pytest

from kungfu_tpu.datasets import ElasticDataset


def collect(ds, n):
    return [ds.next_batch() for _ in range(n)]


class TestSharding:
    def test_disjoint_and_complete_cover(self):
        x = np.arange(64)
        seen = []
        for rank in range(4):
            ds = ElasticDataset([x], batch_size=4, rank=rank, size=4, seed=1)
            for (b,) in collect(ds, ds.batches_per_epoch()):
                seen.extend(b.tolist())
        assert sorted(seen) == list(range(64))

    def test_ranks_agree_on_permutation(self):
        x = np.arange(32)
        d0 = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=9)
        d1 = ElasticDataset([x], batch_size=4, rank=1, size=2, seed=9)
        (b0,) = d0.next_batch()
        (b1,) = d1.next_batch()
        assert set(b0) & set(b1) == set()

    def test_multiple_arrays_stay_aligned(self):
        x = np.arange(40)
        y = np.arange(40) * 10
        ds = ElasticDataset([x, y], batch_size=5, seed=3)
        bx, by = ds.next_batch()
        np.testing.assert_array_equal(by, bx * 10)

    def test_short_tail_dropped(self):
        ds = ElasticDataset([np.arange(10)], batch_size=3, rank=0, size=1)
        assert ds.batches_per_epoch() == 3

    def test_too_small_raises(self):
        ds = ElasticDataset([np.arange(3)], batch_size=4)
        with pytest.raises(ValueError):
            ds.next_batch()


class TestElasticResume:
    def test_resize_continues_stream(self):
        """Grow 1→2 mid-epoch: the union of what both shapes consumed has
        no overlap with what the old shape consumed after the boundary."""
        x = np.arange(64)
        ds = ElasticDataset([x], batch_size=4, rank=0, size=1, seed=5)
        first = [ds.next_batch()[0] for _ in range(4)]  # 16 samples at np=1
        consumed = ds.consumed
        # resize to 2 workers; both resume from the same global offset
        a = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=5)
        b = ElasticDataset([x], batch_size=4, rank=1, size=2, seed=5)
        a.skip(consumed)
        b.skip(consumed)
        nxt = np.concatenate([a.next_batch()[0], b.next_batch()[0]])
        already = np.concatenate(first)
        assert set(nxt) & set(already) == set()

    def test_skip_resumes_exactly(self):
        x = np.arange(48)
        ds = ElasticDataset([x], batch_size=4, seed=2)
        collect(ds, 3)
        mark = ds.consumed
        (expected,) = ds.next_batch()
        ds2 = ElasticDataset([x], batch_size=4, seed=2)
        ds2.skip(mark)
        (got,) = ds2.next_batch()
        np.testing.assert_array_equal(got, expected)

    def test_unaligned_skip_rounds_up(self):
        x = np.arange(64)
        ds = ElasticDataset([x], batch_size=4, rank=0, size=2, seed=0)
        ds.skip(13)  # global batch is 8 → realigns to 16
        ds.next_batch()
        assert ds.consumed == 24

    def test_epoch_reshuffles(self):
        x = np.arange(16)
        ds = ElasticDataset([x], batch_size=16, seed=4)
        (e0,) = ds.next_batch()
        (e1,) = ds.next_batch()
        assert not np.array_equal(e0, e1)
        assert sorted(e0) == sorted(e1)

    def test_epochs_iterator(self):
        x = np.arange(24)
        ds = ElasticDataset([x], batch_size=6, seed=0)
        batches = list(ds.epochs(2))
        assert len(batches) == 8  # 4 per epoch x 2

    def test_no_shuffle_identity_order(self):
        x = np.arange(12)
        ds = ElasticDataset([x], batch_size=4, shuffle=False)
        np.testing.assert_array_equal(ds.next_batch()[0], [0, 1, 2, 3])


class TestMnistLoader:
    """Real-data loader (reference v1/helpers/mnist analog): IDX parsing,
    hash pinning, cache use, and the air-gapped synthetic fallback."""

    @staticmethod
    def _write_idx(path, arr):
        import struct

        arr = np.asarray(arr, np.uint8)
        magic = 0x800 | arr.ndim
        with open(path, "wb") as f:
            f.write(struct.pack(">I", magic))
            f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
            f.write(arr.tobytes())

    def _make_cache(self, directory, n=32):
        rng = np.random.RandomState(0)
        images = rng.randint(0, 256, size=(n, 28, 28), dtype=np.uint8)
        labels = rng.randint(0, 10, size=(n,), dtype=np.uint8)
        self._write_idx(directory / "train-images-idx3-ubyte", images)
        self._write_idx(directory / "train-labels-idx1-ubyte", labels)
        return images, labels

    def test_cached_raw_idx_needs_verify_off(self, tmp_path):
        from kungfu_tpu.datasets.mnist import load_mnist

        images, labels = self._make_cache(tmp_path)
        # raw extracted files have no pin: a verified load refuses them...
        with pytest.raises((ValueError, RuntimeError)):
            load_mnist("train", cache_dir=str(tmp_path),
                       synthetic_fallback=False, timeout=0.01)
        # ...and the explicit opt-out accepts them
        x, y = load_mnist("train", cache_dir=str(tmp_path), verify=False,
                          timeout=0.01)
        assert x.shape == (32, 784) and x.dtype == np.float32
        np.testing.assert_allclose(x[0], images[0].reshape(-1) / 255.0)
        np.testing.assert_array_equal(y, labels.astype(np.int32))

    def test_gz_hash_pin_rejects_tampering(self, tmp_path):
        import gzip

        from kungfu_tpu.datasets import mnist as M

        images = np.zeros((4, 28, 28), np.uint8)
        labels = np.zeros((4,), np.uint8)
        self._write_idx(tmp_path / "img.tmp", images)
        self._write_idx(tmp_path / "lab.tmp", labels)
        for tmp, gz in [("img.tmp", "train-images-idx3-ubyte.gz"),
                        ("lab.tmp", "train-labels-idx1-ubyte.gz")]:
            with open(tmp_path / tmp, "rb") as fi, gzip.open(tmp_path / gz, "wb") as fo:
                fo.write(fi.read())
            (tmp_path / tmp).unlink()
        # wrong digest (not the pinned canonical files) -> strict mode raises
        with pytest.raises((ValueError, RuntimeError)):
            M.load_mnist("train", cache_dir=str(tmp_path),
                         synthetic_fallback=False, timeout=0.01)
        # default mode degrades to the synthetic stand-in
        x, y = M.load_mnist("train", cache_dir=str(tmp_path), timeout=0.01)
        xs, ys = M.synthetic_mnist()
        np.testing.assert_array_equal(x, xs)
        # verify=False accepts the crafted files
        x, y = M.load_mnist("train", cache_dir=str(tmp_path), verify=False,
                            synthetic_fallback=False, timeout=0.01)
        assert x.shape == (4, 784)

    def test_airgapped_fallback(self, tmp_path):
        from kungfu_tpu.datasets.mnist import load_mnist, synthetic_mnist

        x, y = load_mnist("train", cache_dir=str(tmp_path / "empty"), timeout=0.01)
        xs, ys = synthetic_mnist()
        np.testing.assert_array_equal(x, xs)
        np.testing.assert_array_equal(y, ys)

    def test_airgapped_strict_raises(self, tmp_path):
        from kungfu_tpu.datasets.mnist import load_mnist

        with pytest.raises(RuntimeError):
            load_mnist("train", cache_dir=str(tmp_path / "empty"),
                       synthetic_fallback=False, timeout=0.01)


class TestCifar10:
    def test_synthetic_fallback_deterministic(self, tmp_path, monkeypatch):
        from kungfu_tpu.datasets.cifar import load_cifar10

        monkeypatch.setenv("KF_DATA_DIR", str(tmp_path))
        a = load_cifar10(timeout=0.01, n_synthetic_train=256, n_synthetic_test=64)
        b = load_cifar10(timeout=0.01, n_synthetic_train=256, n_synthetic_test=64)
        (xa, ya), (ta, tya) = a
        (xb, yb), _ = b
        assert xa.shape == (256, 32, 32, 3) and xa.dtype == np.float32
        assert ta.shape == (64, 32, 32, 3)
        assert ya.dtype == np.int32 and set(np.unique(ya)) <= set(range(10))
        assert xa.min() >= 0.0 and xa.max() <= 1.0
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
        # train/test draws differ
        assert not np.array_equal(xa[:64], ta)

    def test_synthetic_is_learnable(self, tmp_path, monkeypatch):
        """Class templates must be separable enough for convergence tests."""
        from kungfu_tpu.datasets.cifar import load_cifar10

        monkeypatch.setenv("KF_DATA_DIR", str(tmp_path))
        (x, y), _ = load_cifar10(timeout=0.01, n_synthetic_train=512)
        x = x.reshape(len(x), -1)
        # nearest-class-mean beats chance by a wide margin
        means = np.stack([x[y == c].mean(0) for c in range(10)])
        pred = np.argmin(
            ((x[:, None, :] - means[None]) ** 2).sum(-1), axis=1
        )
        assert (pred == y).mean() > 0.5

    def test_strict_mode_raises_without_cache(self, tmp_path, monkeypatch):
        from kungfu_tpu.datasets.cifar import load_cifar10

        monkeypatch.setenv("KF_DATA_DIR", str(tmp_path / "empty"))
        with pytest.raises(OSError):
            load_cifar10(synthetic_fallback=False, timeout=0.01)

    def test_bad_pin_rejected(self, tmp_path, monkeypatch):
        from kungfu_tpu.datasets import cifar

        monkeypatch.setenv("KF_DATA_DIR", str(tmp_path))
        d = cifar.data_dir()
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, cifar.ARCHIVE), "wb") as f:
            f.write(b"not a tarball")
        with pytest.raises(ValueError, match="sha256"):
            load_tuple = cifar.load_cifar10(timeout=0.01)


class TestSyncConsumed:
    def test_joiner_adopts_survivor_offset(self):
        import threading

        from kungfu_tpu.comm.engine import CollectiveEngine
        from kungfu_tpu.comm.host import HostChannel
        from kungfu_tpu.datasets import ElasticDataset
        from kungfu_tpu.plan import PeerID, PeerList, Strategy

        peers = PeerList.of(PeerID("127.0.0.1", 27551), PeerID("127.0.0.1", 27552))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        try:
            engines = [CollectiveEngine(c, peers, Strategy.STAR) for c in chans]
            x = np.arange(640, dtype=np.float32)
            dss = [
                ElasticDataset([x], 16, rank=i, size=2, seed=1)
                for i in range(2)
            ]
            dss[0].skip(320)  # survivor is mid-stream; ds 1 is a fresh joiner

            class FakePeer:
                def __init__(self, e):
                    self._e = e

                def engine(self):
                    return self._e

            outs = [None, None]

            def run(i):
                outs[i] = dss[i].sync_consumed(FakePeer(engines[i]))

            ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
            assert outs == [320, 320]
            assert dss[1].consumed == 320
        finally:
            for e in engines:
                e.close()
            for c in chans:
                c.close()


class TestImageNetFolder:
    def _make_tree(self, tmp_path, n_per_class=3):
        from PIL import Image

        root = tmp_path / "imagenet"
        rng = np.random.default_rng(0)
        for ci, wnid in enumerate(["n01440764", "n01443537"]):
            d = root / "train" / wnid
            d.mkdir(parents=True)
            for j in range(n_per_class):
                arr = rng.integers(0, 255, size=(48 + 8 * ci, 64, 3)).astype("uint8")
                Image.fromarray(arr).save(d / f"img{j}.JPEG")
        return str(root)

    def test_folder_scan_decode_shapes(self, tmp_path):
        from kungfu_tpu.datasets import ImageNetFolder

        root = self._make_tree(tmp_path)
        ds = ImageNetFolder(root=root, split="train", image_size=32,
                            batch_size=2, seed=3)
        assert len(ds) == 6 and ds.classes == ["n01440764", "n01443537"]
        x, y = ds.next_batch()
        assert x.shape == (2, 32, 32, 3) and x.dtype == np.float32
        assert y.dtype == np.int32 and set(y) <= {0, 1}

    def test_eval_transform_deterministic(self, tmp_path):
        from kungfu_tpu.datasets import ImageNetFolder

        root = self._make_tree(tmp_path)
        a = ImageNetFolder(root=root, image_size=32, batch_size=2, seed=3,
                           train_transform=False)
        b = ImageNetFolder(root=root, image_size=32, batch_size=2, seed=3,
                           train_transform=False)
        xa, ya = a.next_batch()
        xb, yb = b.next_batch()
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)

    def test_restart_replays_identical_augmentation(self, tmp_path):
        """skip(consumed) + same seed must reproduce the same random crops
        (the recovery contract: a restarted worker sees the same stream)."""
        from kungfu_tpu.datasets import ImageNetFolder

        root = self._make_tree(tmp_path)
        a = ImageNetFolder(root=root, image_size=32, batch_size=2, seed=5)
        a.next_batch()
        mark = a.consumed
        x1, _ = a.next_batch()
        b = ImageNetFolder(root=root, image_size=32, batch_size=2, seed=5)
        b.skip(mark)
        x2, _ = b.next_batch()
        np.testing.assert_array_equal(x1, x2)

    def test_elastic_shard_disjoint(self, tmp_path):
        from kungfu_tpu.datasets import ImageNetFolder

        root = self._make_tree(tmp_path)
        r0 = ImageNetFolder(root=root, image_size=16, batch_size=1, rank=0,
                            size=2, seed=7, train_transform=False)
        r1 = ImageNetFolder(root=root, image_size=16, batch_size=1, rank=1,
                            size=2, seed=7, train_transform=False)
        seen0, _ = r0.next_batch()
        seen1, _ = r1.next_batch()
        assert not np.array_equal(seen0, seen1)

    def test_synthetic_fallback(self, tmp_path, monkeypatch):
        from kungfu_tpu.datasets import ImageNetFolder

        monkeypatch.setenv("KF_DATA_DIR", str(tmp_path))
        ds = ImageNetFolder(image_size=32, batch_size=4, n_synthetic=64,
                            synthetic_classes=10, seed=2)
        x, y = ds.next_batch()
        assert x.shape == (4, 32, 32, 3)
        assert np.isfinite(x).all()
        with pytest.raises(OSError):
            ImageNetFolder(image_size=32, synthetic_fallback=False)


class TestPrefetch:
    def test_yields_device_arrays_in_order(self):
        import jax

        from kungfu_tpu.datasets import prefetch_to_device

        batches = [(np.full((2,), i, np.float32), np.int32(i))
                   for i in range(6)]
        out = list(prefetch_to_device(iter(batches), size=2))
        assert len(out) == 6
        for i, (x, y) in enumerate(out):
            assert isinstance(x, jax.Array)
            np.testing.assert_array_equal(np.asarray(x), np.full((2,), i))
            assert int(y) == i

    def test_overlaps_slow_producer(self):
        """The consumer must see batches staged AHEAD: with a slow
        consumer, the producer should have queued more than one batch by
        the time the consumer asks."""
        import time

        from kungfu_tpu.datasets import prefetch_to_device

        produced = []

        def gen():
            for i in range(4):
                produced.append(i)
                yield np.full((1,), i, np.float32)

        it = prefetch_to_device(gen(), size=3)
        first = next(it)
        time.sleep(0.3)  # producer runs ahead while we "compute"
        assert len(produced) >= 3  # staged beyond the consumed batch
        rest = list(it)
        assert len(rest) == 3
        np.testing.assert_array_equal(np.asarray(first), [0.0])

    def test_worker_exception_propagates(self):
        from kungfu_tpu.datasets import prefetch_to_device

        def gen():
            yield np.zeros((1,), np.float32)
            raise RuntimeError("loader broke")

        it = prefetch_to_device(gen(), size=2)
        next(it)
        with pytest.raises(RuntimeError, match="loader broke"):
            list(it)

    def test_bad_size_rejected(self):
        from kungfu_tpu.datasets import prefetch_to_device

        with pytest.raises(ValueError):
            list(prefetch_to_device(iter([]), size=0))

    def test_abandoned_iterator_releases_worker(self):
        """break-ing out (or re-wrapping on resize) must stop the
        producer thread instead of leaving it pinned on a full queue."""
        import threading

        from kungfu_tpu.datasets import prefetch_to_device

        def gen():
            for i in range(1000):
                yield np.full((1,), i, np.float32)

        it = prefetch_to_device(gen(), size=2)
        next(it)
        it.close()  # what GC/break does
        import time
        deadline = time.time() + 5
        while time.time() < deadline and any(
            t.name == "kf-prefetch" and t.is_alive()
            for t in threading.enumerate()
        ):
            time.sleep(0.05)
        assert not any(t.name == "kf-prefetch" and t.is_alive()
                       for t in threading.enumerate())

    def test_eager_validation(self):
        from kungfu_tpu.datasets import prefetch_to_device

        with pytest.raises(ValueError):
            prefetch_to_device(iter([]), size=0)  # at the CALL, not later
