"""Native C++ kernel tests — cross-checked against numpy (the reference
cross-checks its C++ reduce against framework math the same way, §4)."""

import numpy as np
import pytest

from kungfu_tpu import native

try:
    import ml_dtypes

    BF16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    BF16 = None

OPS = [("sum", np.add), ("min", np.minimum), ("max", np.maximum), ("prod", np.multiply)]
DTYPES = [np.float32, np.float64, np.float16, np.int32, np.int64, np.int16,
          np.uint8, np.uint16, np.uint32, np.uint64, np.int8]


@pytest.fixture(scope="module")
def lib_available():
    # without the native lib every cross-check would vacuously compare
    # numpy against numpy; test_native_build_available still fails loudly
    if not native.available():
        pytest.skip("native library unavailable — cross-check would be vacuous")
    return True


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: np.dtype(d).name)
@pytest.mark.parametrize("op,npf", OPS, ids=[o for o, _ in OPS])
def test_transform2_matches_numpy(dtype, op, npf, lib_available):
    rng = np.random.default_rng(0)
    if np.issubdtype(dtype, np.floating):
        a = (rng.standard_normal(4097) * 4).astype(dtype)
        b = (rng.standard_normal(4097) * 4).astype(dtype)
    else:
        a = rng.integers(1, 7, size=4097).astype(dtype)
        b = rng.integers(1, 7, size=4097).astype(dtype)
    ref = npf(a.copy(), b)
    got = native.transform2(a.copy(), b, op)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.skipif(BF16 is None, reason="ml_dtypes not available")
@pytest.mark.parametrize("op,npf", OPS, ids=[o for o, _ in OPS])
def test_transform2_bfloat16(op, npf, lib_available):
    """bf16 — the TPU gradient wire format — must match numpy's
    round-to-nearest-even exactly."""
    rng = np.random.default_rng(1)
    a = (rng.standard_normal(2048) * 4).astype(BF16)
    b = (rng.standard_normal(2048) * 4).astype(BF16)
    ref = npf(a.copy(), b)
    got = native.transform2(a.copy(), b, op)
    np.testing.assert_array_equal(got.view(np.uint16), ref.view(np.uint16))


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.float16])
@pytest.mark.parametrize("op", ["min", "max"])
def test_min_max_propagate_nan(dtype, op, lib_available):
    """Native min/max must propagate NaN like np.minimum/np.maximum — an
    overflowed gradient on one peer must not be silently masked."""
    nan = np.asarray(np.nan, dtype)
    for a, b in [(1.0, np.nan), (np.nan, 1.0), (np.nan, np.nan)]:
        dst = np.asarray([a], dtype)
        src = np.asarray([b], dtype)
        out = native.transform2(dst, src, op)
        assert np.isnan(out[0]), (a, b, op, dtype)


def test_transform2_inplace_and_mismatch():
    a = np.ones(8, np.float32)
    b = np.full(8, 2.0, np.float32)
    out = native.transform2(a, b, "sum")
    assert out is a
    np.testing.assert_array_equal(a, 3.0)
    with pytest.raises(ValueError):
        native.transform2(a, b.astype(np.float64), "sum")


def test_numpy_fallback(monkeypatch):
    """With the native lib disabled, transform2 must still be correct."""
    monkeypatch.setattr(native, "load", lambda: None)
    a = np.arange(16, dtype=np.float32)
    b = np.ones(16, dtype=np.float32)
    np.testing.assert_array_equal(native.transform2(a.copy(), b, "sum"), a + 1)


def test_native_build_available():
    """The toolchain is baked into this image, so the native path should
    actually be exercised in CI (not silently skipped)."""
    assert native.available()
