"""kf-lint in tier-1: the tree must be clean, and the checkers must
actually catch what they claim to catch (fixtures under
tests/lint_fixtures/ seed known violations).
"""

import json
import os
import shutil
import subprocess
import sys

from kungfu_tpu.analysis import (
    aggschema,
    blockingio,
    collectives,
    envcheck,
    jitpurity,
    lockcheck,
    pylockorder,
    retrydiscipline,
    tracevocab,
    wirecontract,
)
from kungfu_tpu.analysis.cli import main as cli_main, run_checkers
from kungfu_tpu.analysis.core import repo_root

ROOT = repo_root(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")

MINI_REGISTRY = '''"""Mini env registry for lint fixtures.

=================  ===========================
``KF_SELF_SPEC``   this worker's ``host:port``
=================  ===========================
"""
'''


def _tmp_tree(tmp_path, files):
    """Build a minimal repo layout: {relpath: source or fixture name}."""
    for rel, content in files.items():
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        if os.path.exists(os.path.join(FIXTURES, str(content))):
            shutil.copy(os.path.join(FIXTURES, str(content)), dst)
        else:
            dst.write_text(content)
    return str(tmp_path)


class TestTreeIsClean:
    def test_all_checkers_clean_on_tree(self):
        """THE tier-1 gate: every project invariant holds on every run."""
        violations = run_checkers(ROOT)
        assert violations == [], "\n".join(v.render() for v in violations)

    def test_cli_exit_zero_on_tree(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kflint")],
            capture_output=True, timeout=120,
        )
        assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


class TestJitPurity:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        got = {(v.line, v.message.split(": ", 1)[1]) for v in jitpurity.check(root)}
        lines = {line for line, _ in got}
        assert lines == {11, 12, 13, 14, 15, 22, 31, 43}, sorted(got)
        # the suppressed .item() (line 17) must NOT appear
        assert all("allow" not in m for _, m in got)

    def test_one_level_deep_attribution(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        deep = [v for v in jitpurity.check(root) if v.line == 22]
        assert len(deep) == 1
        assert "called from jitted bad_step" in deep[0].message


class TestBlockingIO:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "blocking_io_bad.py"})
        lines = sorted(v.line for v in blockingio.check(root))
        assert lines == [14, 18, 23, 31, 32, 39], lines

    def test_non_threaded_module_out_of_scope(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import urllib.request\n"
                "data = urllib.request.urlopen('http://x')\n",
        })
        assert blockingio.check(root) == []


class TestLockDiscipline:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        got = sorted((v.line, v.message.split(" ")[2].strip("`"))
                     for v in lockcheck.check(root))
        assert [line for line, _ in got] == [21, 22, 27, 37], got

    def test_wrong_mutex_is_reported(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        wrong = [v for v in lockcheck.check(root) if v.line == 27]
        assert wrong and "other_mu_" in wrong[0].message


class TestRetryDiscipline:
    """The shipped bug shapes — the constant-period config-server hammer
    and hot retry loops — must be flagged; bounded, jittered,
    exponentially-backed-off loops must not."""

    def _violations(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "retry_bad.py"})
        return retrydiscipline.check(root)

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message) for v in self._violations(tmp_path))
        assert [line for line, _ in got] == [14, 18, 26, 31], got
        assert "unbounded" in got[0][1]
        assert "constant period" in got[1][1]
        assert "constant period" in got[2][1]
        assert "no backoff" in got[3][1]

    def test_compliant_loops_not_flagged(self, tmp_path):
        flagged = {v.line for v in self._violations(tmp_path)}
        # good_deadline_backoff / good_attempt_ladder / good_jittered_poll
        # / per-target iteration start past the suppressed block
        assert not any(line > 45 for line in flagged), flagged

    def test_suppression_honored(self, tmp_path):
        # the allow() lines (39-45) carry a waived unbounded loop and a
        # waived constant sleep — neither may surface
        flagged = {v.line for v in self._violations(tmp_path)}
        assert not any(38 <= line <= 46 for line in flagged), flagged


class TestCollectiveConsistency:
    """The kf-verify SPMD rule: rank-conditional collectives, constant
    rendezvous-name reuse, and peer-divergent name expressions — including
    the interprocedural helper-behind-a-rank-branch shape."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})
        got = sorted((v.line, v.message) for v in collectives.check(root))
        assert [line for line, _ in got] == [10, 21, 33, 40], got
        assert "rank-conditional branch" in got[0][1]
        assert "called only under rank-conditional branches" in got[1][1]
        assert "reused from" in got[2][1]
        assert "diverges across peers" in got[3][1]

    def test_suppression_honored(self, tmp_path):
        # waived_probe (the allow() line) must not surface
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})
        assert all(v.line < 44 for v in collectives.check(root))

    def test_good_fixture_clean(self, tmp_path):
        """The symmetric root/leaf split, versioned names, and digest
        names — the tree's idioms — must pass untouched."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_good.py"})
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]

    def test_comm_layer_out_of_scope(self, tmp_path):
        # the collective IMPLEMENTATION branches on rank by design
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/comm/mod.py": "collective_bad.py",
        })
        assert collectives.check(root) == []

    def test_helper_called_on_both_sides_is_balanced(self, tmp_path):
        """A helper invoked in BOTH branches of a rank split runs on
        every rank — the interprocedural rule must not flag it."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "def _announce(peer):\n"
                "    peer.channel.barrier(peer.cluster.workers,"
                " name='announce')\n\n\n"
                "def sync(peer):\n"
                "    if peer.rank() == 0:\n"
                "        _announce(peer)\n"
                "    else:\n"
                "        _announce(peer)\n",
        })
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]

    def test_literal_symmetric_split_not_reuse(self, tmp_path):
        """The compliant root/leaf split written with a literal name is
        a balanced pair, not cross-path name reuse."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "def bcast(peer, blob, workers):\n"
                "    if peer.rank() == 0:\n"
                "        peer.channel.broadcast_bytes(blob, workers,"
                " name='boot')\n"
                "        return blob\n"
                "    return peer.channel.broadcast_bytes(None, workers,"
                " name='boot')\n",
        })
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]


class TestWireContract:
    """Python framing vs C++ decoder: the real pair diffs clean, and a
    seeded one-byte mutation on EITHER side is caught (the acceptance
    criterion)."""

    def _tree(self, tmp_path, mutate_host=None, mutate_cpp=None):
        host = open(os.path.join(ROOT, "kungfu_tpu", "comm", "host.py")).read()
        cpp = open(os.path.join(ROOT, "kungfu_tpu", "native",
                                "transport.cpp")).read()
        if mutate_host:
            mutated = mutate_host(host)
            assert mutated != host, "mutation must change the file"
            host = mutated
        if mutate_cpp:
            mutated = mutate_cpp(cpp)
            assert mutated != cpp, "mutation must change the file"
            cpp = mutated
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/comm/host.py": host,
            "kungfu_tpu/native/transport.cpp": cpp,
        })

    def test_real_pair_diffs_clean(self, tmp_path):
        root = self._tree(tmp_path)
        assert wirecontract.check(root) == [], \
            [v.render() for v in wirecontract.check(root)]

    def test_one_byte_python_format_mutation(self, tmp_path):
        # "<IIBH" -> "<IIBI": src_len silently widens to u32
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            'HEAD_FMT = "<IIBH"', 'HEAD_FMT = "<IIBI"'))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("IIBIHI" in m and "IIBHHI" in m for m in msgs), msgs

    def test_one_byte_cpp_prefix_mutation(self, tmp_path):
        # head[11] -> head[12]: the C++ fixed prefix drifts off the wire
        root = self._tree(tmp_path, mutate_cpp=lambda s: s.replace(
            "uint8_t head[11]", "uint8_t head[12]"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("head[12]" in m for m in msgs), msgs

    def test_cpp_field_widening_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate_cpp=lambda s: s.replace(
            "put_u16(out, static_cast<uint16_t>(src.size()));",
            "put_u32(out, static_cast<uint32_t>(src.size()));"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("decode_head field sequence" in m for m in msgs), msgs

    def test_magic_drift_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            "0x4B465450", "0x4B465451"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("kMagic" in m for m in msgs), msgs

    def test_codec_bypass_caught(self, tmp_path):
        """A second raw pack site inside the framing functions is exactly
        how drift starts — flagged even while still byte-identical."""
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            "return HeaderCodec.pack_head(token, conn_type, sb, nb, nbytes)",
            'return struct.pack("<IIBH", MAGIC, token, conn_type, len(sb))'
            ' + sb + struct.pack("<H", len(nb)) + nb'
            ' + struct.pack("<L", nbytes)'))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("bypasses HeaderCodec" in m for m in msgs), msgs

    def test_partial_tree_is_silent(self, tmp_path):
        # fixture layouts without the pair must not fail other checkers
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "env_bad.py"})
        assert wirecontract.check(root) == []

    def test_byte_identical_letter_swap_not_drift(self, tmp_path):
        """"<LLBH" packs byte-for-byte like "<IIBH" — the contract is
        width + order, so a same-width letter swap must diff clean."""
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            'HEAD_FMT = "<IIBH"', 'HEAD_FMT = "<LLBH"'))
        assert wirecontract.check(root) == [], \
            [v.render() for v in wirecontract.check(root)]


class TestLockOrder:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "lockorder_bad.py"})
        got = sorted((v.line, v.message) for v in pylockorder.check(root))
        assert [line for line, _ in got] == [15, 33], got
        assert "lock-order cycle" in got[0][1]
        # the cycle message names both witness edges
        assert "mod.py:22" in got[0][1]
        assert "self-deadlock" in got[1][1]

    def test_good_fixture_clean(self, tmp_path):
        """Consistent global order + RLock re-entry must pass."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "lockorder_good.py"})
        assert pylockorder.check(root) == [], \
            [v.render() for v in pylockorder.check(root)]

    def test_release_inside_with_does_not_crash(self, tmp_path):
        """The lock-handoff pattern (explicit release() inside the with
        body) must scan clean, not crash the gate."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import threading\n\n\n"
                "class Handoff:\n"
                "    def __init__(self):\n"
                "        self.mu = threading.Lock()\n\n"
                "    def hand_over(self):\n"
                "        with self.mu:\n"
                "            self.mu.release()\n",
        })
        assert pylockorder.check(root) == [], \
            [v.render() for v in pylockorder.check(root)]


MINI_TIMELINE = (
    "EVENT_KINDS = frozenset({\n"
    '    "collective", "device", "send", "recv", "retry", "deadline",\n'
    '    "signal", "down", "shrink", "chaos", "step", "mark",\n'
    "})\n"
)


class TestTraceVocab:
    """The observability rule: span()/event() kinds must be string
    literals from timeline.py's EVENT_KINDS — a typo'd kind silently
    vanishes from every kftrace filter instead of erroring."""

    def _tree(self, tmp_path):
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/timeline.py": MINI_TIMELINE,
            "kungfu_tpu/mod.py": "tracevocab_bad.py",
        })

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in tracevocab.check(self._tree(tmp_path)))
        assert [line for line, _ in got] == [12, 16, 21, 25], got
        assert "not in the EVENT_KINDS vocabulary" in got[0][1]
        assert "must be a string literal" in got[1][1]
        assert "without a kind argument" in got[2][1]
        assert "'shrnk'" in got[3][1]

    def test_suppression_honored(self, tmp_path):
        # the waived dynamic kind (allow line) must not surface
        flagged = {v.line for v in tracevocab.check(self._tree(tmp_path))}
        assert not any(line > 26 for line in flagged), flagged

    def test_unrelated_receivers_not_flagged(self, tmp_path):
        # Unrelated.span()/.event() calls at the fixture tail are clean
        got = tracevocab.check(self._tree(tmp_path))
        assert all("Unrelated" not in v.message for v in got)

    def test_vocab_parsed_from_real_tree(self, tmp_path):
        from kungfu_tpu.analysis.tracevocab import _vocabulary
        from kungfu_tpu.monitor.timeline import EVENT_KINDS

        assert _vocabulary(ROOT) == set(EVENT_KINDS)

    def test_no_timeline_module_is_silent(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "tracevocab_bad.py"})
        assert tracevocab.check(root) == []


MINI_AGGREGATOR = (
    "SNAPSHOT_FIELDS = frozenset({\n"
    '    "kfmon", "rank", "step", "counters", "events",\n'
    "})\n"
    "VIEW_FIELDS = frozenset({\n"
    '    "ranks", "stale", "skew", "straggler",\n'
    "})\n"
)


class TestAggSchema:
    """The live-plane sibling of trace-vocab: aggregator.field() names
    and make_snapshot() keywords must be literals from the declared
    SNAPSHOT_FIELDS/VIEW_FIELDS schema — a typo'd field silently empties
    a kftop column instead of erroring."""

    def _tree(self, tmp_path):
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/aggregator.py": MINI_AGGREGATOR,
            "kungfu_tpu/mod.py": "aggschema_bad.py",
        })

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in aggschema.check(self._tree(tmp_path)))
        assert [line for line, _ in got] == [13, 17, 21, 29, 33, 57], got
        assert "'stragler'" in got[0][1]
        assert "must be a string literal" in got[1][1]
        assert "without a field name" in got[2][1]
        assert "'stepp'" in got[3][1]
        assert "**dynamic" in got[4][1]
        # a VIEW-only field in make_snapshot raises at runtime, so lint
        # must flag it too (the union is only valid for field() reads)
        assert "'stale'" in got[5][1]

    def test_suppression_honored(self, tmp_path):
        flagged = {v.line for v in aggschema.check(self._tree(tmp_path))}
        assert 37 not in flagged, flagged  # the waived dynamic read

    def test_unrelated_receivers_not_flagged(self, tmp_path):
        flagged = {v.line for v in aggschema.check(self._tree(tmp_path))}
        assert 51 not in flagged and 52 not in flagged, flagged

    def test_schema_parsed_from_real_tree(self):
        from kungfu_tpu.analysis.aggschema import _schemas
        from kungfu_tpu.monitor.aggregator import SNAPSHOT_FIELDS, VIEW_FIELDS

        got = _schemas(ROOT)
        assert got["SNAPSHOT_FIELDS"] == set(SNAPSHOT_FIELDS)
        assert got["VIEW_FIELDS"] == set(VIEW_FIELDS)

    def test_kftop_is_covered_and_clean(self):
        # the viewer is the rule's main client: in scan scope, no findings
        assert os.path.isfile(
            os.path.join(ROOT, "kungfu_tpu", "monitor", "kftop.py"))
        assert [v for v in aggschema.check(ROOT)
                if "kftop" in v.path] == []

    def test_no_aggregator_module_is_silent(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "aggschema_bad.py"})
        assert aggschema.check(root) == []


class TestBaselineAndJson:
    """kflint --json / --baseline: new rules can land with a suppression
    baseline instead of blocking on legacy findings."""

    def _seeded_root(self, tmp_path):
        return _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})

    def test_json_output(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        rc = cli_main(["--root", root, "--checker", "collective-consistency",
                       "--json"])
        assert rc == 1
        findings = json.loads(capsys.readouterr().out)
        assert len(findings) == 4
        assert {f["checker"] for f in findings} == {"collective-consistency"}
        assert all({"path", "line", "message"} <= set(f) for f in findings)

    def test_baseline_roundtrip(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        baseline = str(tmp_path / "baseline.json")
        # snapshot the legacy findings ...
        rc = cli_main(["--root", root, "--checker", "collective-consistency",
                       "--write-baseline", baseline])
        assert rc == 0
        entries = json.load(open(baseline))
        assert len(entries) == 4
        # ... and the gate passes against them, but fails without them
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency",
                         "--baseline", baseline]) == 0
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency"]) == 1
        capsys.readouterr()

    def test_baseline_does_not_mask_new_findings(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        baseline = str(tmp_path / "baseline.json")
        cli_main(["--root", root, "--checker", "collective-consistency",
                  "--write-baseline", baseline])
        # drop one entry: that finding is now "new" again
        entries = json.load(open(baseline))
        json.dump(entries[:-1], open(baseline, "w"))
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency",
                         "--baseline", baseline]) == 1
        capsys.readouterr()

    def test_malformed_baseline_is_loud(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        bad = tmp_path / "bad.json"
        bad.write_text('{"not": "a list"}')
        assert cli_main(["--root", root, "--baseline", str(bad)]) == 2
        capsys.readouterr()


class TestEnvContract:
    def test_unregistered_read_and_suppression(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py": MINI_REGISTRY,
            "kungfu_tpu/mod.py": "env_bad.py",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_TOTALLY_UNREGISTERED_KNOB" in got[0].message

    def test_dead_registry_entry(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                MINI_REGISTRY.replace(
                    "=================  ===========================\n\"\"\"",
                    "``KF_NEVER_READ``  orphaned entry\n"
                    "=================  ===========================\n\"\"\"",
                ),
            "kungfu_tpu/mod.py":
                "import os\nx = os.environ.get('KF_SELF_SPEC')\n",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_NEVER_READ" in got[0].message
        assert "nothing in the tree reads it" in got[0].message

    def test_seeding_a_real_module_fails_the_gate(self, tmp_path):
        """Acceptance: a drifted KF_* read in a real module flips the
        suite red (simulated on a copied slice of the real tree)."""
        real = open(os.path.join(ROOT, "kungfu_tpu", "utils", "trace.py")).read()
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                open(os.path.join(ROOT, "kungfu_tpu", "utils", "envs.py")).read(),
            "kungfu_tpu/utils/trace.py":
                real + "\n_drift = __import__('os').environ.get('KF_SEEDED_DRIFT')\n",
        })
        got = envcheck.check(root)
        assert any("KF_SEEDED_DRIFT" in v.message for v in got), \
            [v.render() for v in got]
