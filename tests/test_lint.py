"""kf-lint in tier-1: the tree must be clean, and the checkers must
actually catch what they claim to catch (fixtures under
tests/lint_fixtures/ seed known violations).
"""

import json
import os
import shutil
import subprocess
import sys

from kungfu_tpu.analysis import (
    aggschema,
    blockingio,
    collectives,
    envcheck,
    handlecheck,
    jitpurity,
    ledgerschema,
    lockcheck,
    protoverify,
    pylockorder,
    recompilehazard,
    retrydiscipline,
    shardaxis,
    shardspec,
    tracevocab,
    wirecontract,
)
from kungfu_tpu.analysis.cli import SHARD_CHECKERS, main as cli_main, run_checkers
from kungfu_tpu.analysis.core import repo_root

ROOT = repo_root(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")

MINI_REGISTRY = '''"""Mini env registry for lint fixtures.

=================  ===========================
``KF_SELF_SPEC``   this worker's ``host:port``
=================  ===========================
"""
'''


def _tmp_tree(tmp_path, files):
    """Build a minimal repo layout: {relpath: source or fixture name}."""
    for rel, content in files.items():
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        if os.path.exists(os.path.join(FIXTURES, str(content))):
            shutil.copy(os.path.join(FIXTURES, str(content)), dst)
        else:
            dst.write_text(content)
    return str(tmp_path)


class TestTreeIsClean:
    def test_all_checkers_clean_on_tree(self):
        """THE tier-1 gate: every project invariant holds on every run."""
        violations = run_checkers(ROOT)
        assert violations == [], "\n".join(v.render() for v in violations)

    def test_cli_exit_zero_on_tree(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kflint")],
            capture_output=True, timeout=120,
        )
        assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


class TestJitPurity:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        got = {(v.line, v.message.split(": ", 1)[1]) for v in jitpurity.check(root)}
        lines = {line for line, _ in got}
        assert lines == {11, 12, 13, 14, 15, 22, 31, 43}, sorted(got)
        # the suppressed .item() (line 17) must NOT appear
        assert all("allow" not in m for _, m in got)

    def test_one_level_deep_attribution(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        deep = [v for v in jitpurity.check(root) if v.line == 22]
        assert len(deep) == 1
        assert "called from jitted bad_step" in deep[0].message


class TestBlockingIO:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "blocking_io_bad.py"})
        lines = sorted(v.line for v in blockingio.check(root))
        assert lines == [14, 18, 23, 31, 32, 39], lines

    def test_non_threaded_module_out_of_scope(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import urllib.request\n"
                "data = urllib.request.urlopen('http://x')\n",
        })
        assert blockingio.check(root) == []


class TestLockDiscipline:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        got = sorted((v.line, v.message.split(" ")[2].strip("`"))
                     for v in lockcheck.check(root))
        assert [line for line, _ in got] == [21, 22, 27, 37], got

    def test_wrong_mutex_is_reported(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        wrong = [v for v in lockcheck.check(root) if v.line == 27]
        assert wrong and "other_mu_" in wrong[0].message


class TestRetryDiscipline:
    """The shipped bug shapes — the constant-period config-server hammer
    and hot retry loops — must be flagged; bounded, jittered,
    exponentially-backed-off loops must not."""

    def _violations(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "retry_bad.py"})
        return retrydiscipline.check(root)

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message) for v in self._violations(tmp_path))
        assert [line for line, _ in got] == [14, 18, 26, 31], got
        assert "unbounded" in got[0][1]
        assert "constant period" in got[1][1]
        assert "constant period" in got[2][1]
        assert "no backoff" in got[3][1]

    def test_compliant_loops_not_flagged(self, tmp_path):
        flagged = {v.line for v in self._violations(tmp_path)}
        # good_deadline_backoff / good_attempt_ladder / good_jittered_poll
        # / per-target iteration start past the suppressed block
        assert not any(line > 45 for line in flagged), flagged

    def test_suppression_honored(self, tmp_path):
        # the allow() lines (39-45) carry a waived unbounded loop and a
        # waived constant sleep — neither may surface
        flagged = {v.line for v in self._violations(tmp_path)}
        assert not any(38 <= line <= 46 for line in flagged), flagged


class TestHandleDiscipline:
    """kf-overlap's lifetime rule: every ``*_async`` handle is waited on
    every control-flow path, never dropped, and never held across a
    membership-change entry point."""

    def _violations(self, tmp_path, fixture):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": fixture})
        return handlecheck.check(root)

    def test_bad_fixture_all_shapes_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in self._violations(tmp_path, "handle_bad.py"))
        assert [line for line, _ in got] == \
            [6, 11, 17, 24, 34, 42, 48, 54, 60], got
        assert "dropped" in got[0][1]
        assert "never waited" in got[1][1]
        assert "every control-flow path" in got[2][1]
        assert "every control-flow path" in got[3][1]
        assert "elastic_step" in got[4][1]
        assert "shrink_to_survivors" in got[5][1]
        # the serving plane's membership boundary fences handles too
        assert "mark_worker_dead" in got[6][1]
        # a kf-pipeline stage re-carve is a membership boundary too: a
        # p2p handle tagged under the old stage geometry must not cross
        assert "recarve" in got[7][1]
        assert "recarve_stages_after_shrink" in got[8][1]

    def test_good_fixture_clean(self, tmp_path):
        got = self._violations(tmp_path, "handle_good.py")
        assert got == [], [v.render() for v in got]

    def test_suppression_honored(self, tmp_path):
        src = (
            "def f(engine, x):\n"
            "    engine.all_reduce_async(x)"
            "  # kflint: allow(handle-discipline)\n"
        )
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": src})
        assert handlecheck.check(root) == []

    def test_drain_is_not_an_issue_site(self, tmp_path):
        src = (
            "def f(engine):\n"
            "    engine.drain_async()\n"
            "    n = engine.drain_async(timeout=5)\n"
            "    return n\n"
        )
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": src})
        assert handlecheck.check(root) == []


class TestPersistHandleDiscipline:
    """kf-persist rides the same lifetime rule: a durable-write handle
    is an async handle — dropped/never-waited persists leak, and no
    handle (persist or collective) may straddle ``persist_fence`` /
    ``restore_from_manifest`` / ``elastic_step``."""

    def _violations(self, tmp_path, fixture):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": fixture})
        return handlecheck.check(root)

    def test_bad_fixture_all_shapes_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in self._violations(tmp_path, "persist_bad.py"))
        assert [line for line, _ in got] == [6, 11, 17, 24, 30], got
        assert "dropped" in got[0][1]
        assert "never waited" in got[1][1]
        # the restore is a membership-change boundary: a persist handle
        # still in flight there may belong to the OLD geometry
        assert "restore_from_manifest" in got[2][1]
        # and the plane's own fence is a fence for EVERY handle kind —
        # a collective handle must not straddle it either
        assert "persist_fence" in got[3][1]
        assert "elastic_step" in got[4][1]

    def test_good_fixture_clean(self, tmp_path):
        got = self._violations(tmp_path, "persist_good.py")
        assert got == [], [v.render() for v in got]


class TestCollectiveConsistency:
    """The kf-verify SPMD rule: rank-conditional collectives, constant
    rendezvous-name reuse, and peer-divergent name expressions — including
    the interprocedural helper-behind-a-rank-branch shape."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})
        got = sorted((v.line, v.message) for v in collectives.check(root))
        assert [line for line, _ in got] == [10, 21, 33, 40], got
        assert "rank-conditional branch" in got[0][1]
        assert "called only under rank-conditional branches" in got[1][1]
        assert "reused from" in got[2][1]
        assert "diverges across peers" in got[3][1]

    def test_suppression_honored(self, tmp_path):
        # waived_probe (the allow() line) must not surface
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})
        assert all(v.line < 44 for v in collectives.check(root))

    def test_good_fixture_clean(self, tmp_path):
        """The symmetric root/leaf split, versioned names, and digest
        names — the tree's idioms — must pass untouched."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_good.py"})
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]

    def test_comm_layer_out_of_scope(self, tmp_path):
        # the collective IMPLEMENTATION branches on rank by design
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/comm/mod.py": "collective_bad.py",
        })
        assert collectives.check(root) == []

    def test_helper_called_on_both_sides_is_balanced(self, tmp_path):
        """A helper invoked in BOTH branches of a rank split runs on
        every rank — the interprocedural rule must not flag it."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "def _announce(peer):\n"
                "    peer.channel.barrier(peer.cluster.workers,"
                " name='announce')\n\n\n"
                "def sync(peer):\n"
                "    if peer.rank() == 0:\n"
                "        _announce(peer)\n"
                "    else:\n"
                "        _announce(peer)\n",
        })
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]

    def test_literal_symmetric_split_not_reuse(self, tmp_path):
        """The compliant root/leaf split written with a literal name is
        a balanced pair, not cross-path name reuse."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "def bcast(peer, blob, workers):\n"
                "    if peer.rank() == 0:\n"
                "        peer.channel.broadcast_bytes(blob, workers,"
                " name='boot')\n"
                "        return blob\n"
                "    return peer.channel.broadcast_bytes(None, workers,"
                " name='boot')\n",
        })
        assert collectives.check(root) == [], \
            [v.render() for v in collectives.check(root)]


class TestWireContract:
    """Python framing vs C++ decoder: the real pair diffs clean, and a
    seeded one-byte mutation on EITHER side is caught (the acceptance
    criterion)."""

    def _tree(self, tmp_path, mutate_host=None, mutate_cpp=None):
        host = open(os.path.join(ROOT, "kungfu_tpu", "comm", "host.py")).read()
        cpp = open(os.path.join(ROOT, "kungfu_tpu", "native",
                                "transport.cpp")).read()
        if mutate_host:
            mutated = mutate_host(host)
            assert mutated != host, "mutation must change the file"
            host = mutated
        if mutate_cpp:
            mutated = mutate_cpp(cpp)
            assert mutated != cpp, "mutation must change the file"
            cpp = mutated
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/comm/host.py": host,
            "kungfu_tpu/native/transport.cpp": cpp,
        })

    def test_real_pair_diffs_clean(self, tmp_path):
        root = self._tree(tmp_path)
        assert wirecontract.check(root) == [], \
            [v.render() for v in wirecontract.check(root)]

    def test_one_byte_python_format_mutation(self, tmp_path):
        # "<IIBH" -> "<IIBI": src_len silently widens to u32
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            'HEAD_FMT = "<IIBH"', 'HEAD_FMT = "<IIBI"'))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("IIBIHI" in m and "IIBHHI" in m for m in msgs), msgs

    def test_one_byte_cpp_prefix_mutation(self, tmp_path):
        # head[11] -> head[12]: the C++ fixed prefix drifts off the wire
        root = self._tree(tmp_path, mutate_cpp=lambda s: s.replace(
            "uint8_t head[11]", "uint8_t head[12]"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("head[12]" in m for m in msgs), msgs

    def test_cpp_field_widening_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate_cpp=lambda s: s.replace(
            "put_u16(out, static_cast<uint16_t>(src.size()));",
            "put_u32(out, static_cast<uint32_t>(src.size()));"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("decode_head field sequence" in m for m in msgs), msgs

    def test_magic_drift_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            "0x4B465450", "0x4B465451"))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("kMagic" in m for m in msgs), msgs

    def test_codec_bypass_caught(self, tmp_path):
        """A second raw pack site inside the framing functions is exactly
        how drift starts — flagged even while still byte-identical."""
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            "return HeaderCodec.pack_head(token, conn_type, sb, nb, nbytes)",
            'return struct.pack("<IIBH", MAGIC, token, conn_type, len(sb))'
            ' + sb + struct.pack("<H", len(nb)) + nb'
            ' + struct.pack("<L", nbytes)'))
        msgs = [v.message for v in wirecontract.check(root)]
        assert any("bypasses HeaderCodec" in m for m in msgs), msgs

    def test_partial_tree_is_silent(self, tmp_path):
        # fixture layouts without the pair must not fail other checkers
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "env_bad.py"})
        assert wirecontract.check(root) == []

    def test_byte_identical_letter_swap_not_drift(self, tmp_path):
        """"<LLBH" packs byte-for-byte like "<IIBH" — the contract is
        width + order, so a same-width letter swap must diff clean."""
        root = self._tree(tmp_path, mutate_host=lambda s: s.replace(
            'HEAD_FMT = "<IIBH"', 'HEAD_FMT = "<LLBH"'))
        assert wirecontract.check(root) == [], \
            [v.render() for v in wirecontract.check(root)]


class TestLockOrder:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "lockorder_bad.py"})
        got = sorted((v.line, v.message) for v in pylockorder.check(root))
        assert [line for line, _ in got] == [15, 33], got
        assert "lock-order cycle" in got[0][1]
        # the cycle message names both witness edges
        assert "mod.py:22" in got[0][1]
        assert "self-deadlock" in got[1][1]

    def test_good_fixture_clean(self, tmp_path):
        """Consistent global order + RLock re-entry must pass."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "lockorder_good.py"})
        assert pylockorder.check(root) == [], \
            [v.render() for v in pylockorder.check(root)]

    def test_release_inside_with_does_not_crash(self, tmp_path):
        """The lock-handoff pattern (explicit release() inside the with
        body) must scan clean, not crash the gate."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import threading\n\n\n"
                "class Handoff:\n"
                "    def __init__(self):\n"
                "        self.mu = threading.Lock()\n\n"
                "    def hand_over(self):\n"
                "        with self.mu:\n"
                "            self.mu.release()\n",
        })
        assert pylockorder.check(root) == [], \
            [v.render() for v in pylockorder.check(root)]


MINI_TIMELINE = (
    "EVENT_KINDS = frozenset({\n"
    '    "collective", "device", "send", "recv", "retry", "deadline",\n'
    '    "signal", "down", "shrink", "chaos", "step", "mark",\n'
    "})\n"
)


class TestTraceVocab:
    """The observability rule: span()/event() kinds must be string
    literals from timeline.py's EVENT_KINDS — a typo'd kind silently
    vanishes from every kftrace filter instead of erroring."""

    def _tree(self, tmp_path):
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/timeline.py": MINI_TIMELINE,
            "kungfu_tpu/mod.py": "tracevocab_bad.py",
        })

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in tracevocab.check(self._tree(tmp_path)))
        assert [line for line, _ in got] == [12, 16, 21, 25], got
        assert "not in the EVENT_KINDS vocabulary" in got[0][1]
        assert "must be a string literal" in got[1][1]
        assert "without a kind argument" in got[2][1]
        assert "'shrnk'" in got[3][1]

    def test_suppression_honored(self, tmp_path):
        # the waived dynamic kind (allow line) must not surface
        flagged = {v.line for v in tracevocab.check(self._tree(tmp_path))}
        assert not any(line > 26 for line in flagged), flagged

    def test_unrelated_receivers_not_flagged(self, tmp_path):
        # Unrelated.span()/.event() calls at the fixture tail are clean
        got = tracevocab.check(self._tree(tmp_path))
        assert all("Unrelated" not in v.message for v in got)

    def test_vocab_parsed_from_real_tree(self, tmp_path):
        from kungfu_tpu.analysis.tracevocab import _vocabulary
        from kungfu_tpu.monitor.timeline import EVENT_KINDS

        assert _vocabulary(ROOT) == set(EVENT_KINDS)

    def test_no_timeline_module_is_silent(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "tracevocab_bad.py"})
        assert tracevocab.check(root) == []


MINI_AGGREGATOR = (
    "SNAPSHOT_FIELDS = frozenset({\n"
    '    "kfmon", "rank", "step", "counters", "events",\n'
    "})\n"
    "VIEW_FIELDS = frozenset({\n"
    '    "ranks", "stale", "skew", "straggler",\n'
    "})\n"
)


class TestAggSchema:
    """The live-plane sibling of trace-vocab: aggregator.field() names
    and make_snapshot() keywords must be literals from the declared
    SNAPSHOT_FIELDS/VIEW_FIELDS schema — a typo'd field silently empties
    a kftop column instead of erroring."""

    def _tree(self, tmp_path):
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/aggregator.py": MINI_AGGREGATOR,
            "kungfu_tpu/mod.py": "aggschema_bad.py",
        })

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in aggschema.check(self._tree(tmp_path)))
        assert [line for line, _ in got] == [13, 17, 21, 29, 33, 57], got
        assert "'stragler'" in got[0][1]
        assert "must be a string literal" in got[1][1]
        assert "without a field name" in got[2][1]
        assert "'stepp'" in got[3][1]
        assert "**dynamic" in got[4][1]
        # a VIEW-only field in make_snapshot raises at runtime, so lint
        # must flag it too (the union is only valid for field() reads)
        assert "'stale'" in got[5][1]

    def test_suppression_honored(self, tmp_path):
        flagged = {v.line for v in aggschema.check(self._tree(tmp_path))}
        assert 37 not in flagged, flagged  # the waived dynamic read

    def test_unrelated_receivers_not_flagged(self, tmp_path):
        flagged = {v.line for v in aggschema.check(self._tree(tmp_path))}
        assert 51 not in flagged and 52 not in flagged, flagged

    def test_schema_parsed_from_real_tree(self):
        from kungfu_tpu.analysis.aggschema import _schemas
        from kungfu_tpu.monitor.aggregator import SNAPSHOT_FIELDS, VIEW_FIELDS

        got = _schemas(ROOT)
        assert got["SNAPSHOT_FIELDS"] == set(SNAPSHOT_FIELDS)
        assert got["VIEW_FIELDS"] == set(VIEW_FIELDS)

    def test_kftop_is_covered_and_clean(self):
        # the viewer is the rule's main client: in scan scope, no findings
        assert os.path.isfile(
            os.path.join(ROOT, "kungfu_tpu", "monitor", "kftop.py"))
        assert [v for v in aggschema.check(ROOT)
                if "kftop" in v.path] == []

    def test_no_aggregator_module_is_silent(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "aggschema_bad.py"})
        assert aggschema.check(root) == []


MINI_LEDGER = (
    "LEDGER_FIELDS = frozenset({\n"
    '    "kfledger", "actor", "knob", "old", "new",\n'
    '    "evidence", "verdict", "effect_series",\n'
    "})\n"
)


class TestLedgerSchema:
    """The decision-ledger sibling of agg-schema: ledger.lfield() names
    and ledger_record()/record_decision() keywords must be literals from
    the declared LEDGER_FIELDS schema — a typo'd field silently drops a
    decision's evidence from the offline replay instead of erroring."""

    def _tree(self, tmp_path):
        return _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/ledger.py": MINI_LEDGER,
            "kungfu_tpu/mod.py": "ledgerschema_bad.py",
        })

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message)
                     for v in ledgerschema.check(self._tree(tmp_path)))
        assert [line for line, _ in got] == [13, 17, 21, 29, 33, 41], got
        assert "'actr'" in got[0][1]
        assert "must be a string literal" in got[1][1]
        assert "without a field name" in got[2][1]
        assert "'knbo'" in got[3][1]
        assert "**dynamic" in got[4][1]
        assert "'evidnce'" in got[5][1]

    def test_suppression_honored(self, tmp_path):
        flagged = {v.line
                   for v in ledgerschema.check(self._tree(tmp_path))}
        assert 45 not in flagged, flagged  # the waived dynamic read

    def test_unrelated_receivers_not_flagged(self, tmp_path):
        flagged = {v.line
                   for v in ledgerschema.check(self._tree(tmp_path))}
        assert 57 not in flagged and 58 not in flagged, flagged

    def test_schema_mutation_is_caught(self, tmp_path):
        # mutation check: drop "verdict" from the declared schema and the
        # previously-clean read at line 9 must surface — proving the rule
        # reads the live declaration rather than a hardcoded field list
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/monitor/ledger.py":
                MINI_LEDGER.replace('"verdict", ', ""),
            "kungfu_tpu/mod.py": "ledgerschema_bad.py",
        })
        flagged = {v.line for v in ledgerschema.check(root)}
        assert 9 in flagged, flagged

    def test_schema_parsed_from_real_tree(self):
        from kungfu_tpu.analysis.ledgerschema import _schema
        from kungfu_tpu.monitor.ledger import LEDGER_FIELDS

        assert _schema(ROOT) == set(LEDGER_FIELDS)

    def test_actors_are_covered_and_clean(self):
        # every adaptive actor writes through record_decision: in scan
        # scope, no findings anywhere in the real tree
        assert ledgerschema.check(ROOT) == []

    def test_no_ledger_module_is_silent(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/mod.py": "ledgerschema_bad.py"})
        assert ledgerschema.check(root) == []


class TestBaselineAndJson:
    """kflint --json / --baseline: new rules can land with a suppression
    baseline instead of blocking on legacy findings."""

    def _seeded_root(self, tmp_path):
        return _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "collective_bad.py"})

    def test_json_output(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        rc = cli_main(["--root", root, "--checker", "collective-consistency",
                       "--json"])
        assert rc == 1
        findings = json.loads(capsys.readouterr().out)
        assert len(findings) == 4
        assert {f["checker"] for f in findings} == {"collective-consistency"}
        assert all({"path", "line", "message"} <= set(f) for f in findings)

    def test_baseline_roundtrip(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        baseline = str(tmp_path / "baseline.json")
        # snapshot the legacy findings ...
        rc = cli_main(["--root", root, "--checker", "collective-consistency",
                       "--write-baseline", baseline])
        assert rc == 0
        entries = json.load(open(baseline))
        assert len(entries) == 4
        # ... and the gate passes against them, but fails without them
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency",
                         "--baseline", baseline]) == 0
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency"]) == 1
        capsys.readouterr()

    def test_baseline_does_not_mask_new_findings(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        baseline = str(tmp_path / "baseline.json")
        cli_main(["--root", root, "--checker", "collective-consistency",
                  "--write-baseline", baseline])
        # drop one entry: that finding is now "new" again
        entries = json.load(open(baseline))
        json.dump(entries[:-1], open(baseline, "w"))
        assert cli_main(["--root", root, "--checker",
                         "collective-consistency",
                         "--baseline", baseline]) == 1
        capsys.readouterr()

    def test_malformed_baseline_is_loud(self, tmp_path, capsys):
        root = self._seeded_root(tmp_path)
        bad = tmp_path / "bad.json"
        bad.write_text('{"not": "a list"}')
        assert cli_main(["--root", root, "--baseline", str(bad)]) == 2
        capsys.readouterr()


class TestEnvContract:
    def test_unregistered_read_and_suppression(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py": MINI_REGISTRY,
            "kungfu_tpu/mod.py": "env_bad.py",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_TOTALLY_UNREGISTERED_KNOB" in got[0].message

    def test_dead_registry_entry(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                MINI_REGISTRY.replace(
                    "=================  ===========================\n\"\"\"",
                    "``KF_NEVER_READ``  orphaned entry\n"
                    "=================  ===========================\n\"\"\"",
                ),
            "kungfu_tpu/mod.py":
                "import os\nx = os.environ.get('KF_SELF_SPEC')\n",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_NEVER_READ" in got[0].message
        assert "nothing in the tree reads it" in got[0].message

    def test_seeding_a_real_module_fails_the_gate(self, tmp_path):
        """Acceptance: a drifted KF_* read in a real module flips the
        suite red (simulated on a copied slice of the real tree)."""
        real = open(os.path.join(ROOT, "kungfu_tpu", "utils", "trace.py")).read()
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                open(os.path.join(ROOT, "kungfu_tpu", "utils", "envs.py")).read(),
            "kungfu_tpu/utils/trace.py":
                real + "\n_drift = __import__('os').environ.get('KF_SEEDED_DRIFT')\n",
        })
        got = envcheck.check(root)
        assert any("KF_SEEDED_DRIFT" in v.message for v in got), \
            [v.render() for v in got]


def _shard_check_all(root):
    return (shardaxis.check(root) + shardspec.check(root)
            + recompilehazard.check(root))


class TestShardAxis:
    """The kf-shard axis rule: literal collective axes must be declared
    by SOME mesh (vocabulary layer — the one-token-typo backbone) and
    bound in EVERY statically-known calling context (environment
    layer)."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_axis_bad.py"})
        got = sorted((v.line, v.message) for v in shardaxis.check(root))
        assert [line for line, _ in got] == [16, 28, 44], got
        assert "no Mesh/pmap in the tree declares" in got[0][1]
        # the env-layer finding names the live environment AND the entry
        assert "not bound in the axis environment {x}" in got[1][1]
        assert "shard_map at" in got[1][1]
        assert "default axis 'zz'" in got[2][1]

    def test_suppression_honored(self, tmp_path):
        # the waived psum("q") on the allow() line must not surface
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_axis_bad.py"})
        assert all(v.line != 19 for v in shardaxis.check(root))

    def test_good_fixture_clean(self, tmp_path):
        """partial(shard_map, mesh=...), nested sub-mesh, two-mesh
        helper with parameter axes, P(None, 'x') — all compliant idioms
        must pass all three kf-shard rules untouched."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_good.py"})
        assert _shard_check_all(root) == [], \
            [v.render() for v in _shard_check_all(root)]

    def test_two_mesh_helper_no_cross_contamination(self, tmp_path):
        """A helper with a LITERAL axis reached from two meshes with
        different axis sets: valid under mesh A, a hang under mesh B —
        the union of the two environments must NOT mask it."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n"
                "import numpy as np\n"
                "from jax.experimental.shard_map import shard_map\n"
                "from jax.sharding import Mesh, PartitionSpec as P\n\n\n"
                "def helper(a):\n"
                "    return jax.lax.psum(a, 'x')\n\n\n"
                "def build():\n"
                "    mx = Mesh(np.array(jax.devices()), ('x',))\n"
                "    my = Mesh(np.array(jax.devices()), ('y',))\n\n"
                "    def bx(a):\n"
                "        return helper(a)\n\n"
                "    def by(a):\n"
                "        return helper(a)\n\n"
                "    fx = shard_map(bx, mesh=mx, in_specs=(P('x'),),\n"
                "                   out_specs=P())\n"
                "    fy = shard_map(by, mesh=my, in_specs=(P(None, 'y'),),\n"
                "                   out_specs=P())\n"
                "    return fx, fy\n",
        })
        got = shardaxis.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert got[0].line == 8
        assert "not bound in the axis environment {y}" in got[0].message

    def test_pmap_axis_name_binds_environment(self, tmp_path):
        """pmap(f, axis_name=...) declares the axis and binds it in the
        mapped body; other declared axes are still unbound there."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n"
                "import numpy as np\n"
                "from jax.sharding import Mesh\n\n"
                "MESH = Mesh(np.array(jax.devices()), ('x',))\n\n\n"
                "def body(g):\n"
                "    ok = jax.lax.psum(g, 'batch')\n"
                "    return ok + jax.lax.psum(g, 'x')\n\n\n"
                "def build():\n"
                "    return jax.pmap(body, axis_name='batch')\n",
        })
        got = shardaxis.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert got[0].line == 10
        assert "'x'" in got[0].message
        assert "not bound in the axis environment {batch}" in got[0].message

    def test_vocabulary_from_constant_table(self, tmp_path):
        """Axis constants resolve through module-level tables and
        imports, the way parallel/mesh.py declares them."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/meshmod.py":
                "import jax\nimport numpy as np\n"
                "from jax.sharding import Mesh\n\n"
                "AXIS_A = 'a'\nAXES = (AXIS_A, 'b')\n\n\n"
                "def build():\n"
                "    return Mesh(np.array(jax.devices()), AXES)\n",
            "kungfu_tpu/user.py":
                "import jax\n"
                "from kungfu_tpu.meshmod import AXIS_A\n\n\n"
                "def ok(g):\n"
                "    return jax.lax.psum(g, AXIS_A)\n\n\n"
                "def bad(g):\n"
                "    return jax.lax.psum(g, 'c')\n",
        })
        got = shardaxis.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert got[0].path.endswith("user.py") and "'c'" in got[0].message


class TestShardSpec:
    """PartitionSpec validity: axis-vs-mesh, duplicates, and
    in_specs/out_specs arity against the mapped function."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_spec_bad.py"})
        got = sorted((v.line, v.message) for v in shardspec.check(root))
        assert [line for line, _ in got] == [18, 21, 23, 30, 33, 40], got
        assert "declares only {x, y}" in got[0][1]          # in_specs axis
        assert "twice" in got[1][1]                          # duplicate
        assert "takes 2 positional parameter(s)" in got[2][1]  # in arity
        assert "returns a 2-tuple" in got[3][1]              # out arity
        assert "NamedSharding" in got[4][1]                  # NamedSharding
        assert "no Mesh/pmap in the tree declares" in got[5][1]  # vocab

    def test_suppression_honored(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_spec_bad.py"})
        # the waived P("qq") (allow line) must not surface
        assert all("qq" not in v.message for v in shardspec.check(root))

    def test_unconstrained_dims_clean(self, tmp_path):
        """PartitionSpec(None, 'x') — None is an unconstrained dim."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax.sharding import Mesh, PartitionSpec as P\n\n\n"
                "def build():\n"
                "    mesh = Mesh(np.array(jax.devices()), ('x',))\n"
                "    return P(None, 'x'), P(), P(('x',), None)\n",
        })
        assert shardspec.check(root) == [], \
            [v.render() for v in shardspec.check(root)]


class TestRecompileHazard:
    """Resize-safety: membership constants, static-arg hazards, and
    world-size closure leaks in compiled code."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "recompile_bad.py"})
        got = sorted((v.line, v.message)
                     for v in recompilehazard.check(root))
        assert [line for line, _ in got] == [10, 11, 12, 22, 31, 32, 33], got
        assert "device_count()" in got[0][1]
        assert "len(peers)" in got[1][1]
        assert "environment read" in got[2][1]
        assert "closes over `world`" in got[3][1]
        assert "per-step-varying" in got[4][1]
        assert "out of range" in got[5][1]
        assert "static_argnames" in got[6][1]

    def test_suppression_honored(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "recompile_bad.py"})
        assert all(v.line != 13 for v in recompilehazard.check(root))

    def test_epoch_scoped_comm_not_flagged(self, tmp_path):
        """comm.size closed into a per-epoch step builder is the
        SANCTIONED pattern (zero.py) — it must stay clean."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "recompile_bad.py"})
        flagged = {v.line for v in recompilehazard.check(root)}
        assert not any(line >= 37 for line in flagged), flagged

    def test_mesh_closure_not_flagged(self, tmp_path):
        """Closing over a Mesh built from jax.devices() is THE shard_map
        pattern — the mesh is rebuilt per epoch by construction."""
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "shard_good.py"})
        assert recompilehazard.check(root) == [], \
            [v.render() for v in recompilehazard.check(root)]


class TestShardMutationProof:
    """The acceptance criterion: a one-token axis-name flip in
    parallel/tp.py (or train.py) and a one-axis PartitionSpec flip in
    parallel/zero.py must flip kflint red; the unmutated files pass all
    three rules with no baseline."""

    _FILES = ("mesh.py", "tp.py", "zero.py", "train.py", "ring.py",
              "moe.py")

    def _tree(self, tmp_path, mutate=None):
        files = {}
        for fn in self._FILES:
            src = open(os.path.join(
                ROOT, "kungfu_tpu", "parallel", fn)).read()
            if mutate and fn in mutate:
                mutated = mutate[fn](src)
                assert mutated != src, f"mutation must change {fn}"
                src = mutated
            files[f"kungfu_tpu/parallel/{fn}"] = src
        return _tmp_tree(tmp_path, files)

    def test_unmutated_parallel_clean(self, tmp_path):
        root = self._tree(tmp_path)
        assert _shard_check_all(root) == [], \
            [v.render() for v in _shard_check_all(root)]

    def test_tp_axis_token_flip_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate={
            "tp.py": lambda s: s.replace(
                "jax.lax.psum(g, axis)", 'jax.lax.psum(g, "tq")'),
        })
        got = [v for v in shardaxis.check(root)
               if v.path.endswith("tp.py")]
        assert got and "'tq'" in got[0].message, \
            [v.render() for v in shardaxis.check(root)]

    def test_train_axis_token_flip_caught(self, tmp_path):
        # flipping the ppermute's pipeline axis to a typo'd token
        root = self._tree(tmp_path, mutate={
            "train.py": lambda s: s.replace(
                "jax.lax.ppermute(out, AXIS_PP, perm)",
                'jax.lax.ppermute(out, "ppx", perm)'),
        })
        got = [v for v in shardaxis.check(root)
               if v.path.endswith("train.py")]
        assert got and "'ppx'" in got[0].message

    def test_zero_partition_spec_flip_caught(self, tmp_path):
        root = self._tree(tmp_path, mutate={
            "zero.py": lambda s: s.replace(
                "lambda s: P(axes) if s.ndim else P(), state_shapes",
                "lambda s: P('dq') if s.ndim else P(), state_shapes"),
        })
        got = [v for v in shardspec.check(root)
               if v.path.endswith("zero.py")]
        assert got and "'dq'" in got[0].message

    def test_mutations_fail_the_cli(self, tmp_path, capsys):
        """The same flip through the kflint CLI (what check.sh runs)."""
        root = self._tree(tmp_path, mutate={
            "tp.py": lambda s: s.replace(
                "jax.lax.psum(g, axis)", 'jax.lax.psum(g, "tq")'),
        })
        args = ["--root", root]
        for c in SHARD_CHECKERS:
            args += ["--checker", c]
        assert cli_main(args) == 1
        capsys.readouterr()


class TestJitSyncInterprocedural:
    """The migrated jit-sync: host syncs are found at ANY call depth
    from the jitted root, not one module-local level."""

    def test_depth_two_sync_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_deep.py"})
        got = jitpurity.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert got[0].line == 18
        assert "in jit scope `level2`" in got[0].message
        assert "called from jitted step" in got[0].message

    def test_static_shape_locals_stay_legal(self, tmp_path):
        """int() over shape-derived locals (moe.py's capacity math) is
        trace-static and must not be flagged at interprocedural depth."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n\n\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    return helper(x)\n\n\n"
                "def helper(x):\n"
                "    t = x.shape[0]\n"
                "    cap = int(max(1, t * 2))\n"
                "    bad = int(x)\n"
                "    return cap + bad\n",
        })
        got = jitpurity.check(root)
        assert [v.line for v in got] == [12], [v.render() for v in got]


class TestSingleParse:
    """The kflint perf satellite: one full run parses each file exactly
    once — the module cache in analysis/core.py is shared by all
    eighteen rules AND the call graph AND the kf-det taint engine."""

    def test_each_file_parsed_once_per_run(self, tmp_path):
        from kungfu_tpu.analysis import core

        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py": MINI_REGISTRY,
            "kungfu_tpu/mod.py": "collective_bad.py",
            "kungfu_tpu/mod2.py": "shard_axis_bad.py",
            "kungfu_tpu/mod3.py": "env_bad.py",
        })
        core.clear_parse_cache()
        run_checkers(root)
        counts = {p: c for p, c in core.PARSE_COUNTS.items()
                  if p.startswith(str(tmp_path))}
        assert len(counts) == 4, counts
        assert all(c == 1 for c in counts.values()), counts

    def test_full_tree_single_parse(self):
        """On the REAL tree — every checker plus the taint engine plus
        the call graph plus the axis env still cost one parse per file
        (the <10s full-run budget depends on this)."""
        from kungfu_tpu.analysis import core

        core.clear_parse_cache()
        run_checkers(ROOT)
        counts = {p: c for p, c in core.PARSE_COUNTS.items()
                  if p.startswith(os.path.join(ROOT, "kungfu_tpu"))}
        over = {p: c for p, c in counts.items() if c != 1}
        assert counts and not over, over

    def test_cache_invalidates_on_rewrite(self, tmp_path):
        """Rewriting a file between runs re-parses it (stat-keyed cache,
        so fixture tests that mutate trees stay correct)."""
        import time

        from kungfu_tpu.analysis import core

        mod = tmp_path / "kungfu_tpu" / "mod.py"
        _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "env_bad.py"})
        core.clear_parse_cache()
        first = core.parse_module(str(mod))
        mod.write_text("x = 1\n")
        second = core.parse_module(str(mod))
        assert first.source != second.source
        assert core.PARSE_COUNTS[str(mod)] == 2


class TestReviewRegressions:
    """Pins for the code-review findings on the kf-shard landing."""

    def test_bound_method_shard_map_arity_clean(self, tmp_path):
        """shard_map(self._body, ...) diffs in_specs against the CALLED
        arity — `self` must not count as a missing spec entry."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax.experimental.shard_map import shard_map\n"
                "from jax.sharding import Mesh, PartitionSpec as P\n\n\n"
                "class Owner:\n"
                "    def __init__(self):\n"
                "        self.mesh = Mesh(np.array(jax.devices()), ('x',))\n\n"
                "    def _body(self, a):\n"
                "        return a\n\n"
                "    def build(self):\n"
                "        return shard_map(self._body, mesh=self.mesh,\n"
                "                         in_specs=(P('x'),),\n"
                "                         out_specs=P('x'))\n",
        })
        assert shardspec.check(root) == [], \
            [v.render() for v in shardspec.check(root)]

    def test_all_gather_dim_kwarg_does_not_shadow_axis(self, tmp_path):
        """lax.all_gather(g, 'typo', axis=0): the int DIMENSION kwarg
        must not shadow the positional axis-NAME typo."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax.sharding import Mesh\n\n"
                "MESH = Mesh(np.array(jax.devices()), ('x',))\n\n\n"
                "def f(g):\n"
                "    return jax.lax.all_gather(g, 'tq', axis=0, tiled=True)\n",
        })
        got = shardaxis.check(root)
        assert len(got) == 1 and "'tq'" in got[0].message, \
            [v.render() for v in got]

    def test_traced_prod_get_still_syncs(self, tmp_path):
        """float(x.prod()) / state.get() on traced values are host
        syncs; int(os.environ.get(...)) is trace-static config."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import os\n\nimport jax\n\n\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    bad = float(x.prod())\n"
                "    ok = int(os.environ.get('KF_K', '4'))\n"
                "    return bad + ok\n",
        })
        got = jitpurity.check(root)
        assert [v.line for v in got] == [8], [v.render() for v in got]

    def test_clear_parse_cache_cascades_to_derived_caches(self, tmp_path):
        """Rewriting a file in the SAME root + clear_parse_cache() must
        re-derive the call graph and axis environment — stale caches
        would silently return the pre-rewrite findings."""
        from kungfu_tpu.analysis import core

        src_ok = (
            "import jax\nimport numpy as np\n"
            "from jax.sharding import Mesh\n\n"
            "MESH = Mesh(np.array(jax.devices()), ('x',))\n\n\n"
            "def f(g):\n"
            "    return jax.lax.psum(g, 'x')\n"
        )
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": src_ok})
        assert shardaxis.check(root) == []
        (tmp_path / "kungfu_tpu" / "mod.py").write_text(
            src_ok.replace("psum(g, 'x')", "psum(g, 'typo')"))
        core.clear_parse_cache()
        got = shardaxis.check(root)
        assert len(got) == 1 and "'typo'" in got[0].message, \
            [v.render() for v in got]

    def test_syntax_error_file_fails_the_suite(self, tmp_path):
        """An unparseable module is invisible to every rule — jit-sync
        owns surfacing it so the suite can't go green unanalyzed."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py": "def broken(:\n    pass\n",
        })
        got = jitpurity.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "syntax error prevents analysis" in got[0].message

    def test_module_level_jit_wrapping_in_scope(self, tmp_path):
        """`train_step = jax.jit(step)` at module level enters jit
        scope — the pre-callgraph checker saw these; the axisenv map
        must too."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n\n\n"
                "def step(x):\n"
                "    return x.item()\n\n\n"
                "train_step = jax.jit(step)\n",
        })
        got = jitpurity.check(root)
        assert len(got) == 1 and got[0].line == 5, \
            [v.render() for v in got]

    def test_np_prod_on_traced_value_still_syncs(self, tmp_path):
        """float(np.prod(x)) concretizes a tracer — flagged; shape-fed
        np.prod stays trace-static."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n\n\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    bad = float(np.prod(x))\n"
                "    ok = int(np.prod(x.shape))\n"
                "    return bad + ok\n",
        })
        got = jitpurity.check(root)
        assert [v.line for v in got] == [7], [v.render() for v in got]

    def test_kwonly_static_argnames_clean(self, tmp_path):
        """Keyword-only params are legal static_argnames targets."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n\n\n"
                "def f(x, *, donate):\n"
                "    return x if donate else -x\n\n\n"
                "g = jax.jit(f, static_argnames='donate')\n",
        })
        assert recompilehazard.check(root) == [], \
            [v.render() for v in recompilehazard.check(root)]

    def test_restricted_dirs_exclude_scan_files(self, tmp_path):
        """iter_py_files(dirs=('kungfu_tpu',)) must not widen to the
        top-level scan files a deliberately-scoped rule excluded."""
        from kungfu_tpu.analysis.core import iter_py_files

        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py": "x = 1\n",
            "__graft_entry__.py": "y = 2\n",
        })
        default = {os.path.basename(p) for p in iter_py_files(root)}
        narrowed = {os.path.basename(p)
                    for p in iter_py_files(root, dirs=("kungfu_tpu",))}
        assert "__graft_entry__.py" in default
        assert "__graft_entry__.py" not in narrowed

    def test_nested_binding_definition_order_independent(self, tmp_path):
        """The inner-mesh body defined BEFORE the function that maps the
        outer body: the fixpoint must not freeze a stale inner-only
        context (definition-order-dependent false positive)."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax.experimental.shard_map import shard_map\n"
                "from jax.sharding import Mesh, PartitionSpec as P\n\n"
                "INNER = Mesh(np.array(jax.devices()[:2]), ('y',))\n"
                "OUTER = Mesh(np.array(jax.devices()), ('x',))\n\n\n"
                "def outer_body(a):\n"
                "    def inner_body(b):\n"
                "        s = jax.lax.psum(b, 'y')\n"
                "        return jax.lax.psum(s, 'x')\n\n"
                "    return shard_map(inner_body, mesh=INNER,\n"
                "                     in_specs=(P('y'),),\n"
                "                     out_specs=P('y'))(a)\n\n\n"
                "def make():\n"
                "    return shard_map(outer_body, mesh=OUTER,\n"
                "                     in_specs=(P('x'),),\n"
                "                     out_specs=P('x'))\n",
        })
        assert shardaxis.check(root) == [], \
            [v.render() for v in shardaxis.check(root)]

    def test_lax_axis_size_is_trace_static(self, tmp_path):
        """int(lax.axis_size(...)) is the suite's own prescribed remedy
        for membership constants — jit-sync must not flag it."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax import lax\n"
                "from jax.sharding import Mesh\n\n"
                "MESH = Mesh(np.array(jax.devices()), ('dp',))\n\n\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    n = int(lax.axis_size('dp'))\n"
                "    return x / n\n",
        })
        assert jitpurity.check(root) == [], \
            [v.render() for v in jitpurity.check(root)]

    def test_bound_method_jit_wrapping_in_scope(self, tmp_path):
        """`train = jax.jit(t.step)` marks the same-module method as
        traced (the pre-callgraph over-report stance for jit SCOPE)."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n\n\n"
                "class Trainer:\n"
                "    def step(self, x):\n"
                "        return x.item()\n\n\n"
                "t = Trainer()\n"
                "train = jax.jit(t.step)\n",
        })
        got = jitpurity.check(root)
        assert len(got) == 1 and got[0].line == 6, \
            [v.render() for v in got]

    def test_decorator_pmap_declares_and_binds_axis(self, tmp_path):
        """@partial(jax.pmap, axis_name='batch') declares the axis AND
        binds it in the decorated body; other axes stay unbound."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "from functools import partial\n\n"
                "import jax\n\n\n"
                "@partial(jax.pmap, axis_name='batch')\n"
                "def ok(g):\n"
                "    return jax.lax.psum(g, 'batch')\n\n\n"
                "@partial(jax.pmap, axis_name='batch')\n"
                "def bad(g):\n"
                "    return jax.lax.psum(g, 'other')\n",
        })
        got = shardaxis.check(root)
        assert len(got) == 1 and got[0].line == 13, \
            [v.render() for v in got]
        assert "'other'" in got[0].message

    def test_import_resolution_needs_dotted_boundary(self, tmp_path):
        """`from core import f` (out-of-tree) must not suffix-match an
        unrelated in-tree module and mark its `f` as jitted."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/score.py":
                "def f(x):\n"
                "    return x.item()\n",
            "kungfu_tpu/user.py":
                "import jax\n"
                "from core import f\n\n"
                "g = jax.jit(f)\n",
        })
        assert jitpurity.check(root) == [], \
            [v.render() for v in jitpurity.check(root)]

    def test_repeated_constant_references_resolve(self, tmp_path):
        """AXES = (A, B) with A and B aliasing the same constant must
        still evaluate (the cycle guard is a stack, not a visited set)."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\nimport numpy as np\n"
                "from jax.sharding import Mesh\n\n"
                "AXIS_DP = 'dp'\n"
                "A = AXIS_DP\n"
                "B = AXIS_DP\n"
                "AXES = (A, B)\n"
                "MESH = Mesh(np.array(jax.devices()), AXES)\n\n\n"
                "def f(g):\n"
                "    return jax.lax.psum(g, 'dp')\n",
        })
        assert shardaxis.check(root) == [], \
            [v.render() for v in shardaxis.check(root)]

    def test_static_local_chain_in_reverse_order(self, tmp_path):
        """A 4-link shape-derived chain assigned in reverse textual
        order is still trace-static (closure runs to convergence)."""
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import jax\n\n\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    for _ in range(2):\n"
                "        d = c * 2\n"
                "        c = b * 2\n"
                "        b = a * 2\n"
                "        a = x.shape[0]\n"
                "    return int(d) + x\n",
        })
        assert jitpurity.check(root) == [], \
            [v.render() for v in jitpurity.check(root)]

    def test_parse_cache_one_entry_per_path(self, tmp_path):
        """A rewritten file REPLACES its cache entry (no unbounded
        accumulation of historical parses)."""
        import time

        from kungfu_tpu.analysis import core

        mod = tmp_path / "kungfu_tpu" / "mod.py"
        _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "x = 1\n"})
        core.clear_parse_cache()
        core.parse_module(str(mod))
        for i in range(5):
            mod.write_text(f"x = {i} + 100\n" * (i + 1))
            core.parse_module(str(mod))
        entries = [k for k in core._MODULE_CACHE if k == str(mod)]
        assert len(entries) == 1, core._MODULE_CACHE.keys()


class TestProtoVerify:
    """The kf-verify SPMD protocol verifier (docs/lint.md).  Exact-line
    pins on the bad fixtures; geometry/mutation coverage lives in
    tests/test_protoverify.py."""

    def _check(self, tmp_path, fixture):
        from kungfu_tpu.analysis import callgraph, core
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": fixture})
        core.clear_parse_cache()
        callgraph.invalidate_cache()
        return protoverify.check(root)

    def test_good_fixture_clean(self, tmp_path):
        got = self._check(tmp_path, "proto_good_mirror.py")
        assert got == [], [v.render() for v in got]

    def test_order_divergence_caught(self, tmp_path):
        """One-sided rank guard + both halves of the uniform bucket
        swap (reduce_scatter and all_gather tags run b{N-1-i})."""
        got = self._check(tmp_path, "proto_bad_order.py")
        assert {v.line for v in got} == {9, 15, 18}, \
            [v.render() for v in got]
        assert any("one side of a rank-dependent" in v.message
                   or "rank" in v.message for v in got if v.line == 9)
        assert all("canonical" in v.message
                   for v in got if v.line in (15, 18))

    def test_orphan_tags_caught(self, tmp_path):
        got = self._check(tmp_path, "proto_bad_orphan.py")
        assert {v.line for v in got} == {8, 11}, \
            [v.render() for v in got]

    def test_fence_cycle_caught(self, tmp_path):
        """Mirror arms that each post a recv, fence, then send — both
        ranks block inside the fence (2-rank simulation)."""
        got = self._check(tmp_path, "proto_bad_cycle.py")
        assert {v.line for v in got} == {8}, [v.render() for v in got]
        assert any("deadlock" in v.message for v in got)

    def test_proto_flag_registered(self):
        from kungfu_tpu.analysis.cli import CHECKERS, PROTO_CHECKERS
        assert PROTO_CHECKERS == (protoverify.CHECKER,)
        assert protoverify.CHECKER in CHECKERS
