"""kf-lint in tier-1: the tree must be clean, and the checkers must
actually catch what they claim to catch (fixtures under
tests/lint_fixtures/ seed known violations).
"""

import os
import shutil
import subprocess
import sys

from kungfu_tpu.analysis import (
    blockingio,
    envcheck,
    jitpurity,
    lockcheck,
    retrydiscipline,
)
from kungfu_tpu.analysis.cli import run_checkers
from kungfu_tpu.analysis.core import repo_root

ROOT = repo_root(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")

MINI_REGISTRY = '''"""Mini env registry for lint fixtures.

=================  ===========================
``KF_SELF_SPEC``   this worker's ``host:port``
=================  ===========================
"""
'''


def _tmp_tree(tmp_path, files):
    """Build a minimal repo layout: {relpath: source or fixture name}."""
    for rel, content in files.items():
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        if os.path.exists(os.path.join(FIXTURES, str(content))):
            shutil.copy(os.path.join(FIXTURES, str(content)), dst)
        else:
            dst.write_text(content)
    return str(tmp_path)


class TestTreeIsClean:
    def test_all_checkers_clean_on_tree(self):
        """THE tier-1 gate: every project invariant holds on every run."""
        violations = run_checkers(ROOT)
        assert violations == [], "\n".join(v.render() for v in violations)

    def test_cli_exit_zero_on_tree(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kflint")],
            capture_output=True, timeout=120,
        )
        assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


class TestJitPurity:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        got = {(v.line, v.message.split(": ", 1)[1]) for v in jitpurity.check(root)}
        lines = {line for line, _ in got}
        assert lines == {11, 12, 13, 14, 15, 22, 31, 43}, sorted(got)
        # the suppressed .item() (line 17) must NOT appear
        assert all("allow" not in m for _, m in got)

    def test_one_level_deep_attribution(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "jit_sync_bad.py"})
        deep = [v for v in jitpurity.check(root) if v.line == 22]
        assert len(deep) == 1
        assert "called from jitted bad_step" in deep[0].message


class TestBlockingIO:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "blocking_io_bad.py"})
        lines = sorted(v.line for v in blockingio.check(root))
        assert lines == [14, 18, 23, 31, 32, 39], lines

    def test_non_threaded_module_out_of_scope(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import urllib.request\n"
                "data = urllib.request.urlopen('http://x')\n",
        })
        assert blockingio.check(root) == []


class TestLockDiscipline:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        got = sorted((v.line, v.message.split(" ")[2].strip("`"))
                     for v in lockcheck.check(root))
        assert [line for line, _ in got] == [21, 22, 27, 37], got

    def test_wrong_mutex_is_reported(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/native/bad.cpp": "lock_bad.cpp"})
        wrong = [v for v in lockcheck.check(root) if v.line == 27]
        assert wrong and "other_mu_" in wrong[0].message


class TestRetryDiscipline:
    """The shipped bug shapes — the constant-period config-server hammer
    and hot retry loops — must be flagged; bounded, jittered,
    exponentially-backed-off loops must not."""

    def _violations(self, tmp_path):
        root = _tmp_tree(tmp_path, {"kungfu_tpu/mod.py": "retry_bad.py"})
        return retrydiscipline.check(root)

    def test_fixture_violations_caught(self, tmp_path):
        got = sorted((v.line, v.message) for v in self._violations(tmp_path))
        assert [line for line, _ in got] == [14, 18, 26, 31], got
        assert "unbounded" in got[0][1]
        assert "constant period" in got[1][1]
        assert "constant period" in got[2][1]
        assert "no backoff" in got[3][1]

    def test_compliant_loops_not_flagged(self, tmp_path):
        flagged = {v.line for v in self._violations(tmp_path)}
        # good_deadline_backoff / good_attempt_ladder / good_jittered_poll
        # / per-target iteration start past the suppressed block
        assert not any(line > 45 for line in flagged), flagged

    def test_suppression_honored(self, tmp_path):
        # the allow() lines (39-45) carry a waived unbounded loop and a
        # waived constant sleep — neither may surface
        flagged = {v.line for v in self._violations(tmp_path)}
        assert not any(38 <= line <= 46 for line in flagged), flagged


class TestEnvContract:
    def test_unregistered_read_and_suppression(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py": MINI_REGISTRY,
            "kungfu_tpu/mod.py": "env_bad.py",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_TOTALLY_UNREGISTERED_KNOB" in got[0].message

    def test_dead_registry_entry(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                MINI_REGISTRY.replace(
                    "=================  ===========================\n\"\"\"",
                    "``KF_NEVER_READ``  orphaned entry\n"
                    "=================  ===========================\n\"\"\"",
                ),
            "kungfu_tpu/mod.py":
                "import os\nx = os.environ.get('KF_SELF_SPEC')\n",
        })
        got = envcheck.check(root)
        assert len(got) == 1, [v.render() for v in got]
        assert "KF_NEVER_READ" in got[0].message
        assert "nothing in the tree reads it" in got[0].message

    def test_seeding_a_real_module_fails_the_gate(self, tmp_path):
        """Acceptance: a drifted KF_* read in a real module flips the
        suite red (simulated on a copied slice of the real tree)."""
        real = open(os.path.join(ROOT, "kungfu_tpu", "utils", "trace.py")).read()
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/utils/envs.py":
                open(os.path.join(ROOT, "kungfu_tpu", "utils", "envs.py")).read(),
            "kungfu_tpu/utils/trace.py":
                real + "\n_drift = __import__('os').environ.get('KF_SEEDED_DRIFT')\n",
        })
        got = envcheck.check(root)
        assert any("KF_SEEDED_DRIFT" in v.message for v in got), \
            [v.render() for v in got]
