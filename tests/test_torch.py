"""Torch binding tests — parity with reference pytorch CI
(.github/workflows/pytorch.yaml: torch_simple_example.py + test_torch_ops.py
under np 1..4), here driven in-process over multi-engine thread clusters."""


import numpy as np
import pytest

torch = pytest.importorskip("torch")

from kungfu_tpu.comm.engine import CollectiveEngine
from kungfu_tpu.comm.host import HostChannel
from kungfu_tpu.plan import PeerID, PeerList, Strategy
from kungfu_tpu.torch.ops import clib, collective
from kungfu_tpu.torch.optimizers.sync_sgd import SynchronousSGDOptimizer

from tests._util import run_all

_port = [27000]


def make_engines(n):
    _port[0] += n + 2
    base = _port[0]
    peers = PeerList.of(*(PeerID("127.0.0.1", base + i) for i in range(n)))
    chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
    engines = [CollectiveEngine(c, peers, Strategy.BINARY_TREE_STAR) for c in chans]
    return engines, chans




def close_all(engines, chans):
    for e in engines:
        e.close()
    for c in chans:
        c.close()


class TestClib:
    @pytest.mark.parametrize(
        "dtype",
        [torch.float16, torch.bfloat16, torch.float32, torch.float64,
         torch.int32, torch.int64, torch.uint8, torch.int8],
    )
    def test_roundtrip(self, dtype):
        t = torch.arange(12).reshape(3, 4).to(dtype)
        a = clib.to_numpy(t)
        back = clib.from_numpy(a, t)
        assert back.dtype == dtype
        assert torch.equal(back.reshape(t.shape), t)

    def test_unsupported(self):
        with pytest.raises(TypeError):
            clib.to_numpy(torch.zeros(2, dtype=torch.complex64))


class TestSingleProcess:
    def test_all_reduce_identity(self):
        t = torch.randn(5)
        out = collective.all_reduce(t, engine=None)
        assert torch.equal(out, t)

    def test_broadcast_parameters_noop(self):
        m = torch.nn.Linear(4, 2)
        before = {k: v.clone() for k, v in m.state_dict().items()}
        collective.broadcast_parameters(m.state_dict(), engine=None)
        for k, v in m.state_dict().items():
            assert torch.equal(v, before[k])

    def test_sync_sgd_matches_plain(self):
        torch.manual_seed(0)
        m1 = torch.nn.Linear(4, 2)
        m2 = torch.nn.Linear(4, 2)
        m2.load_state_dict(m1.state_dict())
        o1 = torch.optim.SGD(m1.parameters(), lr=0.1)
        o2 = SynchronousSGDOptimizer(torch.optim.SGD(m2.parameters(), lr=0.1))
        x = torch.randn(8, 4)
        for m, o in ((m1, o1), (m2, o2)):
            o.zero_grad()
            m(x).pow(2).sum().backward()
            o.step()
        for a, b in zip(m1.parameters(), m2.parameters()):
            assert torch.allclose(a, b)


class TestMultiEngine:
    def test_all_reduce_mean(self):
        engines, chans = make_engines(3)
        try:
            tensors = [torch.full((7,), float(i + 1)) for i in range(3)]
            outs = run_all(
                [lambda e=e, t=t: collective.all_reduce(t, op="mean", engine=e, name="t0")
                 for e, t in zip(engines, tensors)]
            )
            for o in outs:
                assert torch.allclose(o, torch.full((7,), 2.0))
        finally:
            close_all(engines, chans)

    def test_all_gather_stacks_ranks(self):
        engines, chans = make_engines(3)
        try:
            tensors = [torch.full((2, 2), float(i)) for i in range(3)]
            outs = run_all(
                [lambda e=e, t=t: collective.all_gather(t, engine=e,
                                                        name="ag0")
                 for e, t in zip(engines, tensors)]
            )
            for o in outs:
                assert o.shape == (3, 2, 2)
                for r in range(3):
                    assert torch.allclose(o[r], torch.full((2, 2),
                                                           float(r)))
        finally:
            close_all(engines, chans)

    def test_async_handles(self):
        engines, chans = make_engines(2)
        try:
            def worker(e, val):
                grads = [torch.full((4,), val), torch.full((3,), 2 * val)]
                handles = [
                    collective.all_reduce_async(g, op="mean", engine=e, name=f"g{i}")
                    for i, g in enumerate(grads)
                ]
                collective.wait_all_handles(handles)
                return grads

            outs = run_all([lambda e=e, v=float(r + 1): worker(e, v)
                            for r, e in enumerate(engines)])
            for grads in outs:
                assert torch.allclose(grads[0], torch.full((4,), 1.5))
                assert torch.allclose(grads[1], torch.full((3,), 3.0))
        finally:
            close_all(engines, chans)

    def test_async_three_ranks_many_grads(self):
        """Regression: a bounded shared thread pool deadlocked when
        ranks x grads exceeded the pool size (blocked waiters starved the
        rank they waited for)."""
        engines, chans = make_engines(3)
        try:
            def worker(e, val):
                grads = [torch.full((4,), val + i) for i in range(3)]
                handles = [
                    collective.all_reduce_async(g, op="sum", engine=e, name=f"m{i}")
                    for i, g in enumerate(grads)
                ]
                collective.wait_all_handles(handles)
                return grads

            outs = run_all(
                [lambda e=e, v=float(r) : worker(e, v) for r, e in enumerate(engines)],
                timeout=30,
            )
            for grads in outs:
                for i, g in enumerate(grads):
                    assert torch.allclose(g, torch.full((4,), 3.0 + 3 * i))
        finally:
            close_all(engines, chans)

    def test_int_mean_rejected(self):
        with pytest.raises(TypeError):
            collective.all_reduce(torch.ones(3, dtype=torch.int64), op="mean")

    def test_broadcast_parameters(self):
        engines, chans = make_engines(2)
        try:
            # models built BEFORE the worker threads start: torch's seed
            # is process-global, so seeding inside the racing workers made
            # rank 0's "seed-0" weights nondeterministic (flaky mismatch
            # against the ref model, with the broadcast itself correct)
            models = []
            for rank in range(2):
                torch.manual_seed(rank)
                models.append(torch.nn.Linear(3, 3))

            def worker(m, e):
                collective.broadcast_parameters(m.state_dict(), engine=e)
                return {k: v.clone() for k, v in m.state_dict().items()}

            outs = run_all([lambda m=m, e=e: worker(m, e)
                            for m, e in zip(models, engines)])
            torch.manual_seed(0)
            ref = torch.nn.Linear(3, 3).state_dict()
            for sd in outs:
                for k in ref:
                    assert torch.allclose(sd[k], ref[k])
        finally:
            close_all(engines, chans)

    def test_sync_sgd_converges_identically(self):
        """Both ranks end with identical weights == serial large-batch SGD."""
        engines, chans = make_engines(2)
        try:
            torch.manual_seed(7)
            X = torch.randn(16, 4)
            w_true = torch.tensor([[1.0], [-2.0], [0.5], [3.0]])
            Y = X @ w_true

            def worker(rank, e):
                torch.manual_seed(1)  # same init on all ranks
                m = torch.nn.Linear(4, 1, bias=False)
                opt = SynchronousSGDOptimizer(
                    torch.optim.SGD(m.parameters(), lr=0.05), engine=e
                )
                xs, ys = X[rank::2], Y[rank::2]
                for _ in range(30):
                    opt.zero_grad()
                    ((m(xs) - ys) ** 2).mean().backward()
                    opt.step()
                return m.weight.detach().clone()

            outs = run_all([lambda r=r, e=e: worker(r, e) for r, e in enumerate(engines)])
            assert torch.allclose(outs[0], outs[1], atol=1e-6)
        finally:
            close_all(engines, chans)
