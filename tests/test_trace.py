"""Tracing subsystem tests (reference TRACE_SCOPE / event-timeline analog)."""

import time

import pytest

from kungfu_tpu.utils import trace


@pytest.fixture(autouse=True)
def _clean():
    trace.reset_trace_stats()
    yield
    trace.reset_trace_stats()


class TestTraceScope:
    def test_disabled_by_default(self, monkeypatch, caplog):
        monkeypatch.delenv(trace.ENABLE_TRACE, raising=False)
        with trace.trace_scope("quiet-op"):
            pass
        assert trace.trace_report() == {}

    def test_records_stats(self):
        with trace.trace_scope("op-a", force=True):
            time.sleep(0.01)
        with trace.trace_scope("op-a", force=True):
            time.sleep(0.01)
        rep = trace.trace_report()
        assert rep["op-a"]["count"] == 2
        assert rep["op-a"]["total_s"] >= 0.02
        assert rep["op-a"]["mean_ms"] >= 10

    def test_env_enables(self, monkeypatch):
        monkeypatch.setenv(trace.ENABLE_TRACE, "true")
        with trace.trace_scope("op-env"):
            pass
        assert trace.trace_report()["op-env"]["count"] == 1

    def test_nested_scopes(self):
        with trace.trace_scope("outer", force=True):
            with trace.trace_scope("inner", force=True):
                pass
        rep = trace.trace_report()
        assert rep["outer"]["count"] == 1
        assert rep["inner"]["count"] == 1

    def test_exception_still_records(self):
        with pytest.raises(ValueError):
            with trace.trace_scope("boom", force=True):
                raise ValueError("x")
        assert trace.trace_report()["boom"]["count"] == 1


class TestTracedDecorator:
    def test_wraps(self):
        @trace.traced(name="fn-x")
        def f(a, b):
            return a + b

        import os

        os.environ[trace.ENABLE_TRACE] = "1"
        try:
            assert f(1, 2) == 3
        finally:
            del os.environ[trace.ENABLE_TRACE]
        assert trace.trace_report()["fn-x"]["count"] == 1


class TestEngineIntegration:
    def test_allreduce_emits_scope(self, monkeypatch):
        """The collective engine's hot path is traced when enabled."""
        import threading

        import numpy as np

        monkeypatch.setenv(trace.ENABLE_TRACE, "1")
        from kungfu_tpu.comm.engine import CollectiveEngine
        from kungfu_tpu.comm.host import HostChannel
        from kungfu_tpu.plan import PeerID, PeerList
        from kungfu_tpu.plan.strategy import Strategy

        peers = PeerList.of(*(PeerID("127.0.0.1", 23100 + i) for i in range(2)))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        engines = [
            CollectiveEngine(c, peers, strategy=Strategy.STAR) for c in chans
        ]
        outs = [None, None]

        def run(i):
            outs[i] = engines[i].all_reduce(np.ones(4, np.float32))

        ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        for c in chans:
            c.close()
        np.testing.assert_allclose(outs[0], 2 * np.ones(4))
        rep = trace.trace_report()
        assert any(k.startswith("engine.all_reduce[") for k in rep)
