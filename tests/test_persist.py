"""kf-persist: the durable state plane (tier-1, docs/persistence.md).

Manifest completeness is the safety property here — a write torn by
the very preemption the plane exists to survive must never become
training state — and the shape-agnostic restore is the exactness
property: a manifest written by N ranks restored onto M ranks must be
bitwise the carve a live re-carve would have produced.  Covers the
manifest format (torn/corrupt segments, partial-beats-nothing,
keep-last-k GC), the re-carve restore in both directions, the handle
plane (period gating, fence accounting, gauges), the restore-time
agreement hop over real host channels, the committed-KV-page
snapshot round-trip incl. a restored serve worker reusing the warm
prefix, the ``preempt:all`` chaos clause, and the ``-restore-from``
supervisor policy.  The full subprocess drill (``make persist-demo``)
rides in the slow tier.
"""

import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from kungfu_tpu import chaos
from kungfu_tpu.chaos.inject import InjectedDeath
from kungfu_tpu.elastic.persist import (FORMAT, ManifestError, PersistPlane,
                                        agreed_manifest_path, choose_manifest,
                                        gc_manifests, manifest_complete,
                                        manifest_dirs, manifest_name,
                                        newest_complete_manifest,
                                        restore_from_manifest)
from kungfu_tpu.elastic.reshard import ZeroBoundary
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.runner.supervise import strip_preempt
from kungfu_tpu.utils import envs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOTAL = 10


def _chunks_of(full, total, n):
    chunk = math.ceil(total / n)
    buf = np.zeros((chunk * n,), full.dtype)
    buf[:total] = full[:total]
    return [buf[r * chunk:(r + 1) * chunk] for r in range(n)]


def _vectors(seed=9):
    rng = np.random.RandomState(seed)
    return {
        "mu": rng.randn(TOTAL).astype(np.float32),
        "nu": rng.randn(TOTAL).astype(np.float32),
    }


def _write_world(root, n, vecs, step=7, cv=0, replicated=None):
    """One complete manifest: n planes, each persisting its own chunk
    of the committed boundary (the host-plane training shape)."""
    mu = _chunks_of(vecs["mu"], TOTAL, n)
    nu = _chunks_of(vecs["nu"], TOTAL, n)
    mdir = None
    for r in range(n):
        b = ZeroBoundary()
        b.commit_local(
            step, {"mu": mu[r], "nu": nu[r], "count": np.int64(step)},
            total=TOTAL, old_n=n, my_old=r)
        plane = PersistPlane(root, r, cluster_version=cv, period_s=0.0,
                             depth=2, keep=10)
        h = plane.persist_async(step, b, replicated=replicated)
        mdir = h.wait()
        plane.close()
    return mdir


# -- manifest completeness ---------------------------------------------------
class TestManifestCompleteness:
    def test_complete_round_trip(self, tmp_path):
        mdir = _write_world(str(tmp_path), 2, _vectors())
        assert manifest_complete(mdir)
        assert newest_complete_manifest(str(tmp_path)) == mdir

    def test_torn_final_segment_rejected(self, tmp_path):
        """THE preemption hazard: a segment truncated mid-write must
        read as 'this rank never committed', in both verify modes."""
        mdir = _write_world(str(tmp_path), 2, _vectors())
        segp = os.path.join(mdir, "rank1.seg.npz")
        with open(segp, "rb") as f:
            data = f.read()
        with open(segp, "wb") as f:
            f.write(data[:-7])
        assert not manifest_complete(mdir)
        assert not manifest_complete(mdir, digest=False)  # size catches it
        assert newest_complete_manifest(str(tmp_path)) is None
        # the new rank whose carve reads the torn file must refuse
        with pytest.raises(ManifestError):
            restore_from_manifest(mdir, 1, 2)

    def test_same_size_corruption_needs_the_digest(self, tmp_path):
        """Bit rot keeps the byte count: only the digest mode sees it —
        which is why GC's size-only shortcut may pick what to KEEP but
        never what to RESTORE."""
        mdir = _write_world(str(tmp_path), 2, _vectors())
        segp = os.path.join(mdir, "rank0.seg.npz")
        with open(segp, "rb") as f:
            data = bytearray(f.read())
        data[len(data) // 2] ^= 0xFF
        with open(segp, "wb") as f:
            f.write(bytes(data))
        assert manifest_complete(mdir, digest=False)  # size still matches
        assert not manifest_complete(mdir)
        with pytest.raises(ManifestError):
            restore_from_manifest(mdir, 0, 2)

    def test_missing_commit_record_is_partial(self, tmp_path):
        mdir = _write_world(str(tmp_path), 2, _vectors())
        os.unlink(os.path.join(mdir, "rank1.ok.json"))
        assert not manifest_complete(mdir)

    def test_newest_complete_beats_newer_partial(self, tmp_path):
        """A preemption mid-persist leaves a newer torn manifest; the
        restore source must be the older one that committed."""
        old = _write_world(str(tmp_path), 2, _vectors(), step=5)
        new = _write_world(str(tmp_path), 2, _vectors(seed=10), step=9)
        os.unlink(os.path.join(new, "rank0.ok.json"))
        assert newest_complete_manifest(str(tmp_path)) == old
        assert choose_manifest(str(tmp_path)) == (5, 0)

    def test_format_mismatch_refuses(self, tmp_path):
        mdir = _write_world(str(tmp_path), 2, _vectors())
        metap = os.path.join(mdir, "meta.json")
        with open(metap) as f:
            meta = json.load(f)
        meta["format"] = FORMAT + 1
        with open(metap, "w") as f:
            json.dump(meta, f)
        with pytest.raises(ManifestError):
            restore_from_manifest(mdir, 0, 2)


# -- GC ----------------------------------------------------------------------
class TestGC:
    def test_keep_last_k(self, tmp_path):
        for s in (1, 2, 3, 4):
            _write_world(str(tmp_path), 2, _vectors(seed=s), step=s)
        removed = gc_manifests(str(tmp_path), keep=2)
        left = [s for s, _, _ in manifest_dirs(str(tmp_path))]
        assert left == [3, 4]
        assert sorted(os.path.basename(p) for p in removed) == \
            [manifest_name(1, 0), manifest_name(2, 0)]

    def test_only_complete_manifest_never_deleted(self, tmp_path):
        """The last restore point survives any keep policy; a stale
        partial OLDER than it goes, a newer partial (maybe still
        landing) is left alone."""
        older = _write_world(str(tmp_path), 2, _vectors(), step=2)
        keeper = _write_world(str(tmp_path), 2, _vectors(), step=5)
        newer = _write_world(str(tmp_path), 2, _vectors(), step=8)
        os.unlink(os.path.join(older, "rank0.ok.json"))
        os.unlink(os.path.join(newer, "rank1.ok.json"))
        removed = gc_manifests(str(tmp_path), keep=1)
        assert removed == [older]
        assert os.path.isdir(keeper) and os.path.isdir(newer)
        # and with nothing complete at all, GC removes nothing
        os.unlink(os.path.join(keeper, "rank0.ok.json"))
        assert gc_manifests(str(tmp_path), keep=1) == []


# -- shape-agnostic restore --------------------------------------------------
class TestRestoreReshard:
    def _restore_all(self, mdir, new_n):
        return [restore_from_manifest(mdir, r, new_n) for r in range(new_n)]

    def _gathered(self, states, leaf):
        chunk = states[0].chunk
        buf = np.zeros((chunk * len(states),), states[0].vec[leaf].dtype)
        for r, st in enumerate(states):
            buf[r * chunk:(r + 1) * chunk] = st.vec[leaf]
        return buf[:TOTAL]

    def test_restore_onto_smaller_world_bitwise(self, tmp_path):
        vecs = _vectors()
        mdir = _write_world(str(tmp_path), 4, vecs, step=7,
                            replicated={"params": np.arange(6, dtype=np.float32)})
        sts = self._restore_all(mdir, 2)
        # dict keys flatten sorted: leaf 0 = count (scalar), 1/2 = mu/nu
        np.testing.assert_array_equal(self._gathered(sts, 1), vecs["mu"])
        np.testing.assert_array_equal(self._gathered(sts, 2), vecs["nu"])
        for st in sts:
            assert st.step == 7 and st.new_n == 2
            assert int(st.scal[0]) == 7
            np.testing.assert_array_equal(
                st.replicated["params"], np.arange(6, dtype=np.float32))

    def test_restore_onto_larger_world_bitwise(self, tmp_path):
        vecs = _vectors(seed=11)
        mdir = _write_world(str(tmp_path), 2, vecs, step=3)
        sts = self._restore_all(mdir, 4)
        np.testing.assert_array_equal(self._gathered(sts, 1), vecs["mu"])
        np.testing.assert_array_equal(self._gathered(sts, 2), vecs["nu"])

    def test_single_rank_round_trip(self, tmp_path):
        vecs = _vectors(seed=12)
        mdir = _write_world(str(tmp_path), 1, vecs, step=2,
                            replicated={"c": np.int64(41)})
        (st,) = self._restore_all(mdir, 1)
        np.testing.assert_array_equal(st.vec[1][:TOTAL], vecs["mu"])
        np.testing.assert_array_equal(st.vec[2][:TOTAL], vecs["nu"])
        assert st.replicated["c"].dtype == np.int64
        assert int(st.replicated["c"]) == 41

    def test_install_into_boundary_continues_live(self, tmp_path):
        """The restored carve seeds the live elastic machinery: the
        boundary's committed chunks are exactly the restored ones."""
        vecs = _vectors(seed=13)
        mdir = _write_world(str(tmp_path), 4, vecs, step=7)
        st = restore_from_manifest(mdir, 1, 2)
        b = ZeroBoundary()
        st.install_into_boundary(b)
        step, vec, scal = b.chunks()
        assert step == 7
        np.testing.assert_array_equal(vec[1], st.vec[1])
        np.testing.assert_array_equal(vec[2], st.vec[2])

    def test_bad_geometry_rejected(self, tmp_path):
        mdir = _write_world(str(tmp_path), 2, _vectors())
        with pytest.raises(ValueError):
            restore_from_manifest(mdir, 2, 2)
        with pytest.raises(ValueError):
            restore_from_manifest(mdir, 0, 0)


# -- the handle plane --------------------------------------------------------
class TestPlaneHandles:
    def _boundary(self, step=1):
        b = ZeroBoundary()
        b.commit_local(step, {"m": np.zeros(TOTAL, np.float32)},
                       total=TOTAL, old_n=1, my_old=0)
        return b

    def test_commit_is_period_gated(self, tmp_path):
        plane = PersistPlane(str(tmp_path), 0, period_s=1000.0)
        try:
            assert plane.commit(1, self._boundary(1)) is not None
            assert plane.commit(2, self._boundary(2)) is None  # too soon
        finally:
            plane.close()

    def test_period_zero_persists_every_commit_and_fence_counts(self, tmp_path):
        plane = PersistPlane(str(tmp_path), 0, period_s=0.0, depth=2, keep=10)
        try:
            for s in (1, 2, 3):
                assert plane.commit(s, self._boundary(s)) is not None
            # depth-2 window: issuing step 3 already settled step 1
            assert plane.persist_fence() <= 2
            assert REGISTRY.gauge("kf_ckpt_last_step").value == 3.0
            assert REGISTRY.gauge("kf_ckpt_age_seconds").value < 60.0
            assert len(manifest_dirs(str(tmp_path))) == 3
        finally:
            plane.close()

    def test_persist_before_any_commit_raises(self, tmp_path):
        plane = PersistPlane(str(tmp_path), 0, period_s=0.0)
        try:
            with pytest.raises(ValueError):
                plane.persist_async(1, ZeroBoundary())
        finally:
            plane.close()


# -- restore-time agreement (the proto-verified hop) -------------------------
class TestAgreement:
    BASE_PORT = 28950

    def _world(self, n):
        from kungfu_tpu.comm.host import HostChannel
        from kungfu_tpu.plan import PeerID, PeerList

        TestAgreement.BASE_PORT += n + 1
        base = TestAgreement.BASE_PORT
        peers = PeerList.of(*(PeerID("127.0.0.1", base + i)
                              for i in range(n)))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        return peers, chans

    def _agree(self, tmp_path, n, choice):
        from tests._util import run_all

        peers, chans = self._world(n)
        planes = [PersistPlane(str(tmp_path), r) for r in range(n)]
        try:
            got = run_all([
                lambda r=r: planes[r].agree_manifest(
                    chans[r], peers, r,
                    *(choice if r == 0 else (-1, -1)))
                for r in range(n)
            ], timeout=60)
        finally:
            for c in chans:
                c.close()
            for p in planes:
                p.close()
        return got

    def test_every_rank_adopts_rank0_choice(self, tmp_path):
        assert self._agree(tmp_path, 3, (7, 2)) == [(7, 2)] * 3
        assert agreed_manifest_path(str(tmp_path), 7, 2) == \
            os.path.join(str(tmp_path), manifest_name(7, 2))

    def test_fresh_start_sentinel_agreed(self, tmp_path):
        assert self._agree(tmp_path, 2, (-1, -1)) == [(-1, -1)] * 2
        assert agreed_manifest_path(str(tmp_path), -1, -1) is None


# -- committed KV-page snapshots ---------------------------------------------
class TestKVSnapshot:
    def _pool(self):
        from kungfu_tpu.serve.kvcache import KVCachePool, PageSpec

        spec = PageSpec(n_layers=2, n_heads=2, head_dim=4, page_tokens=4,
                        dtype="float32")
        return KVCachePool(spec, capacity_pages=8), spec

    def _committed(self, pool, spec, tokens, seed=0):
        rng = np.random.default_rng(seed)
        shape = (spec.n_layers, spec.n_heads, spec.page_tokens,
                 spec.head_dim)
        n_pages = len(tokens) // spec.page_tokens
        data = [(rng.standard_normal(shape).astype(np.float32),
                 rng.standard_normal(shape).astype(np.float32))
                for _ in range(n_pages)]
        pages = pool.alloc(n_pages)
        for pid, (k, v) in zip(pages, data):
            pool.put_page_data(pid, k, v)
        pool.commit_chain(tokens, pages)
        pool.release(pages)
        return data

    def test_round_trip_bitwise(self):
        pool, spec = self._pool()
        tokens = list(range(1, 9))  # 2 full pages of 4
        data = self._committed(pool, spec, tokens)
        snap = pool.snapshot_committed()
        fresh, _ = self._pool()
        assert fresh.restore_committed(snap) == (2, 0)
        pages, n_cached = fresh.lookup(tokens)
        assert n_cached == 8
        for pid, (k, v) in zip(pages, data):
            gk, gv = fresh.page_data(pid)
            np.testing.assert_array_equal(gk, k)
            np.testing.assert_array_equal(gv, v)
        fresh.release(pages)

    def test_corrupt_page_rejected_never_served(self):
        pool, spec = self._pool()
        self._committed(pool, spec, list(range(1, 9)))
        snap = pool.snapshot_committed()
        name = sorted(k for k in snap if k.endswith("_k"))[0]
        snap[name] = snap[name] + np.float32(1e-3)  # flip content
        fresh, _ = self._pool()
        assert fresh.restore_committed(snap) == (1, 1)

    def test_idempotent_restore(self):
        pool, spec = self._pool()
        self._committed(pool, spec, list(range(1, 9)))
        snap = pool.snapshot_committed()
        fresh, _ = self._pool()
        assert fresh.restore_committed(snap) == (2, 0)
        free_before = fresh.free_pages
        # the incumbent keeps the page: no duplicate adoption
        assert fresh.restore_committed(snap) == (2, 0)
        assert fresh.free_pages == free_before


class TestRestoredServeWorker:
    def test_warm_cache_through_cold_restart(self):
        """ISSUE acceptance (serve): a restored worker answers the same
        request token-identically WITH prefix reuse > 0 — the snapshot
        made the cache warm, not just present."""
        jax = pytest.importorskip("jax")
        from kungfu_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        from kungfu_tpu.serve.engine import InferenceEngine
        from kungfu_tpu.serve.kvcache import KVCachePool, PageSpec

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                                n_heads=2, d_ff=64, max_seq=128,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))

        def engine():
            pool = KVCachePool(PageSpec.for_model(cfg, page_tokens=8),
                               capacity_pages=64)
            return InferenceEngine(model, params, pool=pool, max_batch=4,
                                   max_seq=cfg.max_seq)

        prompt = list(range(1, 20))  # 19 tokens: 2 full pages of 8
        old = engine()
        old.submit("before", prompt, 6)
        ref = [e for e in old.drain() if e["kind"] == "done"][0]
        snap = old.pool.snapshot_committed()

        new = engine()  # the cold-restarted worker
        restored, rejected = new.pool.restore_committed(snap)
        assert restored > 0 and rejected == 0
        new.submit("after", prompt, 6)
        evs = new.drain()
        done = [e for e in evs if e["kind"] == "done"][0]
        assert done["reused_tokens"] == 16  # both full pages reused
        assert done["tokens"] == ref["tokens"]


# -- the preempt:all chaos clause --------------------------------------------
class TestChaosPreempt:
    def test_parse_requires_explicit_all(self):
        with pytest.raises(ValueError):
            chaos.parse_spec("preempt:step=2")
        with pytest.raises(ValueError):
            chaos.parse_spec("preempt:rank=1")  # deliberately not scopable
        (c,) = chaos.parse_spec("preempt:all,step=2,mode=raise")
        assert c.kind == "preempt" and c.get("step") == 2

    def test_fires_on_every_rank_at_the_step(self):
        spec = chaos.parse_spec("preempt:all,step=2,mode=raise")
        for rank in (0, 5):  # NOT rank-scoped: preemption means all
            ctl = chaos.ChaosController(spec, rank=rank, seed=0)
            ctl.on_step(1)
            with pytest.raises(InjectedDeath):
                ctl.on_step(2)

    def test_without_step_fires_at_first_boundary(self):
        ctl = chaos.ChaosController(
            chaos.parse_spec("preempt:all,mode=raise"), rank=3, seed=0)
        with pytest.raises(InjectedDeath):
            ctl.on_step(0)


# -- the -restore-from supervisor policy -------------------------------------
class TestSupervisorPolicy:
    def test_strip_preempt_spares_other_clauses(self):
        assert strip_preempt("preempt:all,step=3;delay:ms=5") == "delay:ms=5"
        assert strip_preempt("delay:ms=5;preempt:all") == "delay:ms=5"
        assert strip_preempt("preempt:all") == ""
        assert strip_preempt("") == ""
        assert strip_preempt("die:step=3,rank=1") == "die:step=3,rank=1"

    def test_restore_from_is_its_own_supervisor(self, tmp_path):
        from kungfu_tpu.runner.cli import main

        d = str(tmp_path / "m")
        with pytest.raises(SystemExit):
            main(["-np", "1", "-persist-dir", d, "-restore-from", d,
                  "true"])
        with pytest.raises(SystemExit):
            main(["-np", "1", "-restore-from", d, "-w", "true"])
        with pytest.raises(SystemExit):
            main(["-np", "1", "-restore-from", d, "-auto-recover", "10s",
                  "true"])


class TestEnvKnobs:
    def test_persist_knobs_defaults(self, monkeypatch):
        for key in (envs.PERSIST_DIR, envs.PERSIST_PERIOD,
                    envs.PERSIST_ASYNC_DEPTH, envs.PERSIST_KEEP,
                    envs.PERSIST_RESTORE):
            monkeypatch.delenv(key, raising=False)
        knobs = envs.persist_knobs()
        assert knobs == {"dir": "", "period_s": 30.0, "depth": 2,
                         "keep": 3, "restore": False}

    def test_persist_knobs_reads_env(self, monkeypatch):
        monkeypatch.setenv(envs.PERSIST_DIR, "/ckpt")
        monkeypatch.setenv(envs.PERSIST_PERIOD, "0")
        monkeypatch.setenv(envs.PERSIST_RESTORE, "1")
        knobs = envs.persist_knobs()
        assert knobs["dir"] == "/ckpt"
        assert knobs["period_s"] == 0.0
        assert knobs["restore"] is True


# -- the full drill ----------------------------------------------------------
@pytest.mark.slow
class TestPreemptRestoreE2E:
    def test_demo_preempt_relaunch_and_halved_cold_restart(self):
        """preempt:all kills every rank, the supervisor relaunches from
        the newest complete manifest, and a 2-worker launch re-carves
        the 4-rank manifest — final params bitwise vs replay."""
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "examples",
                                          "preempt_restore.py")],
            capture_output=True, text=True, timeout=280)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "PERSIST DEMO OK" in out.stdout
