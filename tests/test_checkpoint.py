"""Checkpoint engine tests — both backends and cross-format restore."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from kungfu_tpu import checkpoint as ckpt


@pytest.fixture(params=["npz", "orbax"])
def backend(request, monkeypatch):
    if request.param == "orbax" and ckpt._orbax() is None:
        pytest.skip("orbax not installed")
    monkeypatch.setenv("KF_TPU_CKPT_BACKEND", request.param)
    return request.param


def _tree():
    return {
        "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": jnp.ones((3,), jnp.bfloat16),
        "inner": {"step": jnp.int32(7)},
    }


class TestRoundTrip:
    def test_save_restore(self, backend, tmp_path):
        tree = _tree()
        ckpt.save_checkpoint(str(tmp_path), 3, tree, meta={"epoch": 2})
        out = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert out is not None
        got, step, meta = out
        assert step == 3 and meta == {"epoch": 2}
        np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(tree["w"]))
        assert np.asarray(got["b"]).dtype == np.asarray(tree["b"]).dtype
        assert int(got["inner"]["step"]) == 7

    def test_latest_wins(self, backend, tmp_path):
        tree = _tree()
        for s in (1, 5, 3):
            ckpt.save_checkpoint(str(tmp_path), s, tree, meta={"s": s})
        assert ckpt.latest_step(str(tmp_path)) == 5
        _, step, meta = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert step == 5 and meta == {"s": 5}

    def test_restore_empty_dir(self, backend, tmp_path):
        assert ckpt.restore_checkpoint(str(tmp_path / "none"), _tree()) is None

    def test_prune(self, backend, tmp_path):
        tree = _tree()
        for s in range(6):
            ckpt.save_checkpoint(str(tmp_path), s, tree)
        ckpt.prune_checkpoints(str(tmp_path), keep=2)
        steps = sorted(s for s, _ in ckpt._step_entries(str(tmp_path)))
        assert steps == [4, 5]


class TestCrossFormat:
    def test_mixed_history_restores_newest(self, tmp_path, monkeypatch):
        if ckpt._orbax() is None:
            pytest.skip("orbax not installed")
        tree = _tree()
        monkeypatch.setenv("KF_TPU_CKPT_BACKEND", "npz")
        ckpt.save_checkpoint(str(tmp_path), 1, tree, meta={"fmt": "npz"})
        monkeypatch.setenv("KF_TPU_CKPT_BACKEND", "orbax")
        ckpt.save_checkpoint(str(tmp_path), 2, tree, meta={"fmt": "orbax"})
        _, step, meta = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert (step, meta["fmt"]) == (2, "orbax")
        # and the older npz is still individually restorable
        _, step, meta = ckpt.restore_checkpoint(str(tmp_path), tree, step=1)
        assert (step, meta["fmt"]) == (1, "npz")


class TestStructureDrift:
    def test_orbax_leaf_count_mismatch_raises(self, tmp_path, monkeypatch):
        """A model whose structure changed since the checkpoint must fail
        loudly, not silently truncate/mispair parameters (advisor round 1)."""
        if ckpt._orbax() is None:
            pytest.skip("orbax not installed")
        monkeypatch.setenv("KF_TPU_CKPT_BACKEND", "orbax")
        ckpt.save_checkpoint(str(tmp_path), 0, _tree())
        grown = dict(_tree(), extra=np.zeros(2, np.float32))
        with pytest.raises(ValueError, match="structure"):
            ckpt.restore_checkpoint(str(tmp_path), grown)

    def test_orbax_renamed_key_same_count_raises(self, tmp_path, monkeypatch):
        """Equal leaf counts with renamed keys must also fail — count-only
        checks would mispair arrays by flatten order."""
        if ckpt._orbax() is None:
            pytest.skip("orbax not installed")
        monkeypatch.setenv("KF_TPU_CKPT_BACKEND", "orbax")
        ckpt.save_checkpoint(str(tmp_path), 0, _tree())
        renamed = _tree()
        renamed["b_renamed"] = renamed.pop("b")
        with pytest.raises(ValueError, match="structure"):
            ckpt.restore_checkpoint(str(tmp_path), renamed)

    def test_npz_mismatch_fails_loudly(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KF_TPU_CKPT_BACKEND", "npz")
        ckpt.save_checkpoint(str(tmp_path), 0, _tree())
        grown = dict(_tree(), extra=np.zeros(2, np.float32))
        with pytest.raises(KeyError):
            ckpt.restore_checkpoint(str(tmp_path), grown)


class TestAsyncSave:
    def test_async_roundtrip(self, backend, tmp_path):
        tree = _tree()
        fut = ckpt.save_checkpoint_async(str(tmp_path), 3, tree, {"epoch": 3})
        path = fut.result(60)
        assert os.path.exists(path)
        restored, step, meta = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert step == 3 and meta == {"epoch": 3}
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))

    def test_snapshot_is_immune_to_later_mutation(self, backend, tmp_path):
        """The host snapshot happens at call time: mutating the source
        arrays afterwards (a donated train step reusing buffers) must not
        corrupt the write."""
        src = {"w": np.arange(8, dtype=np.float32)}
        fut = ckpt.save_checkpoint_async(str(tmp_path), 1, src)
        src["w"] += 1000.0  # in-place mutation after issue
        fut.result(60)
        restored, _, _ = ckpt.restore_checkpoint(
            str(tmp_path), {"w": np.zeros(8, np.float32)})
        np.testing.assert_array_equal(restored["w"],
                                      np.arange(8, dtype=np.float32))

    def test_wait_pending_surfaces_failure(self, backend, tmp_path):
        bad_dir = os.path.join(str(tmp_path), "file-not-dir")
        with open(bad_dir, "w") as f:
            f.write("x")
        ckpt.save_checkpoint_async(bad_dir, 1, _tree())
        with pytest.raises((OSError, NotADirectoryError, FileExistsError)):
            ckpt.wait_pending_checkpoints(60)
        # queue is drained after the failure is surfaced
        ckpt.wait_pending_checkpoints(5)

    def test_ordering_newest_wins(self, backend, tmp_path):
        for step in (1, 2, 3):
            tree = {"w": jnp.full((4,), float(step), jnp.float32)}
            ckpt.save_checkpoint_async(str(tmp_path), step, tree)
        ckpt.wait_pending_checkpoints(120)
        assert ckpt.latest_step(str(tmp_path)) == 3
        restored, _, _ = ckpt.restore_checkpoint(
            str(tmp_path), {"w": jnp.zeros((4,), jnp.float32)})
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 3.0))
