"""Sanitizer build variants + the TSan transport churn stress.

The fast tests verify the Makefile variant plumbing (separate outputs,
separate flag stamps, loader selection).  The slow test builds the
fully-instrumented ``kfstress-tsan`` binary and runs channel
open/send/close churn under ThreadSanitizer, asserting a clean report —
this is the gate that caught the AF_UNIX accept-loop close hang and the
clockwait/TSan interception pitfall (see native/transport.cpp).
"""

import os
import shutil
import subprocess

import pytest

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "kungfu_tpu", "native",
)

_toolchain = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="no C++ toolchain",
)


def _tsan_supported() -> bool:
    """Probe once whether -fsanitize=thread links on this host."""
    probe = subprocess.run(
        ["g++", "-fsanitize=thread", "-x", "c++", "-", "-o", "/dev/null"],
        input=b"int main(){return 0;}", capture_output=True, timeout=60,
    )
    return probe.returncode == 0


@_toolchain
class TestSanitizerBuilds:
    def test_variant_names_and_stamps(self, tmp_path):
        rc = subprocess.run(
            ["make", "-C", NATIVE_DIR, "-s", "tsan"],
            capture_output=True, timeout=300,
        )
        if rc.returncode != 0:
            pytest.skip(f"tsan build unsupported: {rc.stderr[-200:]!r}")
        assert os.path.exists(os.path.join(NATIVE_DIR, "libkfnative-tsan.so"))
        stamp = os.path.join(NATIVE_DIR, ".buildflags-tsan")
        assert os.path.exists(stamp)
        flags = open(stamp).read()
        assert "-fsanitize=thread" in flags
        # the production stamp must NOT mention sanitizers: variants are
        # flag-stamped independently so they can never mix
        plain = os.path.join(NATIVE_DIR, ".buildflags")
        if os.path.exists(plain):
            assert "-fsanitize" not in open(plain).read()

    def test_loader_selects_variant_path(self):
        from kungfu_tpu import native

        old = os.environ.get("KF_NATIVE_SANITIZE")
        try:
            os.environ["KF_NATIVE_SANITIZE"] = "tsan"
            assert native._lib_path().endswith("libkfnative-tsan.so")
            os.environ["KF_NATIVE_SANITIZE"] = "asan"
            assert native._lib_path().endswith("libkfnative-asan.so")
            os.environ["KF_NATIVE_SANITIZE"] = "nonsense"
            assert native._lib_path().endswith("libkfnative.so")
            os.environ.pop("KF_NATIVE_SANITIZE")
            assert native._lib_path().endswith("libkfnative.so")
        finally:
            if old is None:
                os.environ.pop("KF_NATIVE_SANITIZE", None)
            else:
                os.environ["KF_NATIVE_SANITIZE"] = old


@pytest.mark.slow
@_toolchain
class TestTSanStress:
    def test_channel_churn_clean_under_tsan(self):
        if not _tsan_supported():
            pytest.skip("-fsanitize=thread not supported here")
        rc = subprocess.run(
            ["make", "-C", NATIVE_DIR, "-s", "stress"],
            capture_output=True, timeout=300,
        )
        assert rc.returncode == 0, rc.stderr.decode()[-500:]
        binary = os.path.join(NATIVE_DIR, "kfstress-tsan")
        env = dict(os.environ,
                   TSAN_OPTIONS="halt_on_error=0 exitcode=66",
                   KF_SOCK_DIR="")
        run = subprocess.run(
            [binary, "4"], capture_output=True, timeout=480, env=env,
        )
        err = run.stderr.decode(errors="replace")
        assert run.returncode == 0, f"stress rc={run.returncode}\n{err[-2000:]}"
        assert "WARNING: ThreadSanitizer" not in err, err[-2000:]
        assert "all rounds clean" in err
