"""kf-pipeline: cross-DCN pipeline parallelism (parallel/pp.py).

The bitwise contract is the spine of this file: the distributed 1F1B
run — any interleaving of stages, async handles, prefetched recvs,
ZeRO-2 bucketed DP reduce-scatter — must produce byte-identical params
to the single-process sequential reference built from the SAME stage
modules, because the schedule and the transport are not allowed to
change the math.  The elastic half pins the same property through a
chaos ``die_slice``: one stage re-carve from ring-buddy mirrors, final
params bitwise vs a fixed-world replay (docs/pipeline.md).
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from kungfu_tpu import chaos
from kungfu_tpu.checkpoint import StepSnapshot
from kungfu_tpu.comm.engine import CollectiveEngine
from kungfu_tpu.comm.faults import PeerFailureError
from kungfu_tpu.comm.host import HostChannel
from kungfu_tpu.models.transformer import TransformerConfig
from kungfu_tpu.parallel import pp
from kungfu_tpu.parallel.train import ParallelPlan
from kungfu_tpu.plan import Cluster, PeerID, PeerList, Strategy

from tests._util import run_all

CFG = TransformerConfig(vocab_size=64, d_model=16, n_layers=4, n_heads=2,
                        d_ff=32, max_seq=8, dtype="float32")


@pytest.fixture(autouse=True)
def _fresh_chaos():
    chaos.reset()
    yield
    chaos.reset()


def _engines(n, base_port, monkeypatch):
    monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
    peers = PeerList.of(
        *(PeerID("127.0.0.1", base_port + i) for i in range(n)))
    chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
    return chans, [CollectiveEngine(c, peers, Strategy.STAR)
                   for c in chans]


def _data(seed, B, S=8):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, CFG.vocab_size, (B, S)).astype(np.int32),
            rng.integers(0, CFG.vocab_size, (B, S)).astype(np.int32))


def _tree_equal(a, b) -> bool:
    # host-side compare: the two trees may live on DIFFERENT local
    # device pairs (per-rank tp meshes), which jnp refuses to mix
    eqs = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))),
        a, b)
    return all(jax.tree_util.tree_leaves(eqs))


def _run_world(pipes, shards, steps=1):
    """Drive every rank's train_step in threads; returns per-rank last
    losses."""
    n = len(pipes)
    outs = [None] * n
    errs = []

    def one(i):
        try:
            for _ in range(steps):
                ids, tgt = shards[i]
                outs[i] = pipes[i].train_step(ids, tgt)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errs.append((i, e))

    ts = [threading.Thread(target=one, args=(i,), daemon=True)
          for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(240)
    assert not any(t.is_alive() for t in ts), "pipeline hung"
    assert not errs, errs
    return outs


# -- pure schedule / partition math -----------------------------------------
class TestPartition:
    def test_balanced_contiguous(self):
        assert pp.stage_partition(12, 4) == [(0, 3), (3, 6), (6, 9),
                                             (9, 12)]
        assert pp.stage_partition(7, 3) == [(0, 3), (3, 5), (5, 7)]

    def test_tiles_exactly(self):
        for L in (4, 7, 12, 13):
            for S in range(1, L + 1):
                m = pp.stage_partition(L, S)
                assert m[0][0] == 0 and m[-1][1] == L
                assert all(a[1] == b[0] for a, b in zip(m, m[1:]))
                assert all(hi > lo for lo, hi in m)

    def test_empty_stage_rejected(self):
        with pytest.raises(ValueError, match="cannot fill"):
            pp.stage_partition(2, 3)

    def test_interleaved_groups(self):
        part = pp.interleaved_partition(8, 2, 2)
        # stage s owns virtual stages s, s+S: chunks are non-adjacent
        assert part == [[(0, 2), (4, 6)], [(2, 4), (6, 8)]]


class TestSchedules:
    @pytest.mark.parametrize("m,S", [(4, 2), (3, 2), (2, 4), (8, 4),
                                     (1, 1)])
    def test_1f1b_shape(self, m, S):
        for s in range(S):
            ops = pp.schedule_1f1b(m, S, s)
            fs = [mb for k, mb, _ in ops if k == "F"]
            bs = [mb for k, mb, _ in ops if k == "B"]
            assert fs == list(range(m)) and bs == list(range(m))
            # backward of mb can only run after its forward
            seen_f = set()
            for k, mb, _ in ops:
                if k == "F":
                    seen_f.add(mb)
                else:
                    assert mb in seen_f
            # steady state: at most warmup+1 forwards outstanding
            warm = min(S - 1 - s, m)
            live = 0
            peak = 0
            for k, mb, _ in ops:
                live += 1 if k == "F" else -1
                peak = max(peak, live)
            assert peak <= warm + 1

    def test_sequential_is_strict(self):
        assert pp.schedule_sequential(3, 2, 0) == [
            ("F", 0, 0), ("B", 0, 0), ("F", 1, 0), ("B", 1, 0),
            ("F", 2, 0), ("B", 2, 0)]

    @pytest.mark.parametrize("m,S,v", [(4, 2, 2), (3, 2, 3), (2, 3, 2)])
    def test_interleaved_valid_and_mb_ordered(self, m, S, v):
        per_stage = [pp.schedule_interleaved(m, S, s, v)
                     for s in range(S)]
        V = S * v
        for s, ops in enumerate(per_stage):
            for c in range(v):
                fs = [mb for k, mb, cc in ops if k == "F" and cc == c]
                bs = [mb for k, mb, cc in ops if k == "B" and cc == c]
                # strict microbatch order per chunk = the bitwise
                # gradient-accumulation contract
                assert fs == list(range(m)) and bs == list(range(m))
        # global dependency replay: the merged op streams must be
        # executable with blocking recvs (what the simulator guarantees)
        f_done = [[False] * m for _ in range(V)]
        b_done = [[False] * m for _ in range(V)]
        cursors = [0] * S
        moved = True
        while moved:
            moved = False
            for s in range(S):
                while cursors[s] < len(per_stage[s]):
                    k, mb, c = per_stage[s][cursors[s]]
                    vs = c * S + s
                    if k == "F":
                        ok = vs == 0 or f_done[vs - 1][mb]
                    else:
                        ok = f_done[vs][mb] and (
                            vs == V - 1 or b_done[vs + 1][mb])
                    if not ok:
                        break
                    (f_done if k == "F" else b_done)[vs][mb] = True
                    cursors[s] += 1
                    moved = True
        assert all(c == len(per_stage[s]) for s, c in enumerate(cursors)), \
            "interleaved schedule deadlocked in replay"

    def test_build_schedule_vocabulary(self):
        with pytest.raises(ValueError, match="unknown pp schedule"):
            pp.build_schedule("gpipe", 4, 2, 0)
        with pytest.raises(ValueError, match="interleave"):
            pp.build_schedule("1f1b", 4, 2, 0, v=2)


class TestRecarvePlans:
    def test_stage_recarve_plan_units(self):
        plan = pp.stage_recarve_plan(4, 2, 1)
        # embed stays with stage 0, the final block moves 1 -> 0, and
        # stage 1's layers move to the merged stage
        assert (-1, 0, 0) in plan and (-2, 1, 0) in plan
        assert (2, 1, 0) in plan and (3, 1, 0) in plan

    @pytest.mark.parametrize("old_n,new_n", [(2, 1), (3, 2), (2, 3),
                                             (4, 2), (1, 1)])
    def test_flat_segments_tile_and_preserve_identity(self, old_n, new_n):
        old_map = pp.stage_partition(CFG.n_layers, old_n) \
            if old_n <= CFG.n_layers else None
        if old_map is None:
            pytest.skip("not enough layers")
        new_map = pp.stage_partition(CFG.n_layers, new_n)
        segs = pp.flat_recarve_segments(CFG, old_map, new_map)
        old_lay, old_totals = pp.stage_flat_layouts(CFG, old_map)
        new_lay, new_totals = pp.stage_flat_layouts(CFG, new_map)

        def fill(lays, totals):
            flats = []
            for s, lay in enumerate(lays):
                f = np.zeros(totals[s])
                for key, gr0, rows, rowsize, off in lay:
                    for r in range(rows):
                        base = hash((key, gr0 + r)) % 100003
                        f[off + r * rowsize:off + (r + 1) * rowsize] = \
                            base + np.arange(rowsize) * 1e-7
                flats.append(f)
            return flats

        oldf, want = fill(old_lay, old_totals), fill(new_lay, new_totals)
        got = [np.full(t, np.nan) for t in new_totals]
        cover = [np.zeros(t, bool) for t in new_totals]
        for (os_, oo, ns, no, ln) in segs:
            assert not cover[ns][no:no + ln].any(), "segment overlap"
            cover[ns][no:no + ln] = True
            got[ns][no:no + ln] = oldf[os_][oo:oo + ln]
        for ns in range(new_n):
            assert cover[ns].all(), "new stage flat not tiled"
            assert np.array_equal(got[ns], want[ns])

    def test_chunk_splits_tile(self):
        out = list(pp._chunk_splits(5, 12, 20, 8, 16))
        assert sum(l for *_, l in out) == 20
        pos = 5
        for jo, jn, oo, no, l in out:
            assert oo == pos and no == pos + 7
            assert jo == oo // 8 and jn == no // 16
            assert oo // 8 == (oo + l - 1) // 8
            assert no // 16 == (no + l - 1) // 16
            pos += l


# -- ParallelPlan ------------------------------------------------------------
class TestParallelPlan:
    def test_validation(self):
        with pytest.raises(ValueError, match="zero_stage"):
            ParallelPlan(zero_stage=4)
        with pytest.raises(ValueError, match="pp_schedule"):
            ParallelPlan(pp_schedule="gpipe")
        with pytest.raises(ValueError, match="interleave"):
            ParallelPlan(interleave=2)  # needs the interleaved schedule
        p = ParallelPlan(dp=2, tp=2, pp=3, sp=1, zero_stage=2)
        assert p.size == 12 and p.host_size == 6
        assert p.mesh_plan().pp == 3

    def test_stage_geometry(self):
        p = ParallelPlan(dp=2, pp=3)
        assert p.stage_of(4) == 2 and p.dp_index(4) == 0
        assert p.stage_ranks(1) == [2, 3]
        assert p.stage_map(6) == [(0, 2), (2, 4), (4, 6)]
        topo = p.to_slice_topology()
        assert topo.num_slices == 3 and topo.ranks_per_slice == 2
        assert ParallelPlan(dp=4).to_slice_topology() is None
        assert p.with_stages(2).pp == 2 and p.with_stages(2).dp == 2

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("KF_PP_STAGES", "3")
        monkeypatch.setenv("KF_PP_MICROBATCHES", "6")
        monkeypatch.setenv("KF_PP_SCHEDULE", "sequential")
        p = ParallelPlan.from_env(dp=2)
        assert (p.pp, p.n_micro, p.pp_schedule, p.dp) == \
            (3, 6, "sequential", 2)
        monkeypatch.delenv("KF_PP_STAGES")
        monkeypatch.delenv("KF_PP_MICROBATCHES")
        monkeypatch.delenv("KF_PP_SCHEDULE")
        p = ParallelPlan.from_env()
        assert (p.pp, p.n_micro, p.pp_schedule) == (1, None, "1f1b")

    def test_dp_train_step_rejects_other_axes(self):
        from kungfu_tpu.parallel.train import dp_train_step

        with pytest.raises(ValueError, match="dp-only"):
            dp_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                          plan=ParallelPlan(pp=2))

    def test_zero_train_step_plan_contract(self):
        from kungfu_tpu.parallel.zero import zero_train_step

        with pytest.raises(ValueError, match="zero_stage is 0"):
            zero_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                            plan=ParallelPlan())
        with pytest.raises(ValueError, match="ONE dp axis"):
            zero_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                            plan=ParallelPlan(tp=2, zero_stage=2))
        # an EXPLICIT stage/schedule that disagrees with the plan must
        # raise, never be silently replaced (None defaults make the
        # explicit case distinguishable)
        with pytest.raises(ValueError, match="disagrees with "
                                             "plan.zero_stage"):
            zero_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                            stage=2, plan=ParallelPlan(zero_stage=1))
        with pytest.raises(ValueError, match="disagrees with "
                                             "plan.collective_schedule"):
            zero_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                            schedule="lax",
                            plan=ParallelPlan(
                                zero_stage=2,
                                collective_schedule="pallas_ring"))

    def test_dp_train_step_rejects_unconsumable_arm(self):
        from kungfu_tpu.parallel.train import dp_train_step

        with pytest.raises(ValueError, match="no 'pallas_ring' arm"):
            dp_train_step(lambda p, b: 0.0, optax.sgd(0.1), comm=None,
                          plan=ParallelPlan(
                              collective_schedule="pallas_ring"))

    def test_sharded_trainer_accepts_plan(self):
        from kungfu_tpu.parallel.train import ShardedTrainer

        t = ShardedTrainer(CFG, ParallelPlan(n_micro=2,
                                             collective_schedule="psum"))
        assert t.plan.dp == 1 and t.n_micro == 2
        with pytest.raises(ValueError, match="ZeRO"):
            ShardedTrainer(CFG, ParallelPlan(zero_stage=2))

    def test_serve_engine_rejects_sharded_plan(self):
        from kungfu_tpu.models.transformer import Transformer
        from kungfu_tpu.serve.engine import InferenceEngine

        model = Transformer(CFG)
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(NotImplementedError, match="full-model"):
            InferenceEngine(model, params, plan=ParallelPlan(tp=2),
                            max_batch=2)
        eng = InferenceEngine(model, params, plan=ParallelPlan(dp=3),
                              max_batch=2)
        assert eng.plan.dp == 3


# -- engine p2p ---------------------------------------------------------------
class TestEngineP2P:
    def test_sync_roundtrip(self, monkeypatch):
        chans, engines = _engines(2, 27210, monkeypatch)
        try:
            x = np.arange(8, dtype=np.float32)

            def a():
                engines[0].send_to(1, x, "t.a")
                return engines[0].recv_from(1, "t.b", dtype=np.int32,
                                            shape=(2, 2))

            def b():
                got = engines[1].recv_from(0, "t.a", dtype=np.float32)
                engines[1].send_to(0, np.arange(4, dtype=np.int32), "t.b")
                return got

            ra, rb = run_all([a, b])
            assert np.array_equal(rb, x)
            assert ra.shape == (2, 2)
        finally:
            for c in chans:
                c.close()

    def test_async_handles_settle(self, monkeypatch):
        chans, engines = _engines(2, 27220, monkeypatch)
        try:
            x = np.arange(16, dtype=np.float32)

            def a():
                h = engines[0].send_async(1, x, "u.a")
                return h.wait()

            def b():
                h = engines[1].recv_async(0, "u.a", dtype=np.float32)
                return h.wait()

            na, got = run_all([a, b])
            assert na == x.nbytes
            assert np.array_equal(got, x)
        finally:
            for c in chans:
                c.close()

    def test_p2p_trace_ids_link(self, monkeypatch):
        """Sender and receiver of ONE hop must derive the IDENTICAL
        trace id (op "p2p" on both halves) or the hop never forms a
        cross-rank causal edge in a merged trace."""
        from kungfu_tpu.monitor import timeline

        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        cursor, _ = timeline.events_tail(0)
        chans, engines = _engines(2, 27225, monkeypatch)
        try:
            x = np.arange(8, dtype=np.float32)
            run_all([
                lambda: engines[0].send_to(1, x, "tr.hop"),
                lambda: engines[1].recv_from(0, "tr.hop",
                                             dtype=np.float32),
            ])
            _, evs = timeline.events_tail(cursor)
            traces = {(e.get("attrs") or {}).get("trace")
                      for e in evs
                      if e.get("kind") == "collective"
                      and (e.get("attrs") or {}).get("tag") == "tr.hop"}
            assert len(traces) == 1 and None not in traces, traces
        finally:
            for c in chans:
                c.close()

    def test_typed_failure_at_wait(self, monkeypatch):
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "1.0")
        chans, engines = _engines(2, 27230, monkeypatch)
        chans[1].close()
        try:
            h = engines[0].recv_async(1, "never", dtype=np.float32)
            with pytest.raises(PeerFailureError) as ei:
                h.wait(timeout=30)
            assert ei.value.rank == 1
        finally:
            chans[0].close()


# -- the bitwise spine --------------------------------------------------------
class TestPipelineBitwise:
    @pytest.mark.parametrize("S,m,sched", [
        (2, 4, "1f1b"),     # aligned
        (2, 3, "1f1b"),     # ragged microbatch count
        # deep-pipe variants cost ~20s each on the 1-core box; the slow
        # lane keeps them, tier-1 keeps the shallow spine
        pytest.param(4, 4, "1f1b", marks=pytest.mark.slow),   # deeper pipe
        pytest.param(4, 6, "1f1b", marks=pytest.mark.slow),   # ragged, deeper
        (2, 4, "sequential"),
    ])
    def test_bitwise_vs_reference(self, S, m, sched, monkeypatch):
        plan = ParallelPlan(pp=S, n_micro=m, pp_schedule=sched)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(0))
        inner = optax.sgd(0.05)
        ids, tgt = _data(7, B=m * 2)
        ref_full, ref_losses, _ = pp.reference_pipeline_step(
            CFG, plan, full, [(ids, tgt)], inner)
        chans, engines = _engines(S, 27240 + 10 * S + m, monkeypatch)
        try:
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner) for e in engines]
            outs = _run_world(pipes, [(ids, tgt)] * S)
            assert outs[-1] == pytest.approx(float(np.mean(ref_losses)),
                                             abs=1e-6)
            for pipe in pipes:
                lo, hi = pipe.stage_layers()
                want = pp.slice_stage_params(
                    CFG, ref_full, lo, hi, pipe.stage == 0,
                    pipe.stage == S - 1)
                assert _tree_equal(pipe.params[0], want), \
                    f"stage {pipe.stage} diverged from the reference"
        finally:
            for c in chans:
                c.close()

    @pytest.mark.slow  # ~20s: multi-step 1f1b replay on the 1-core box
    def test_multi_step_bitwise(self, monkeypatch):
        plan = ParallelPlan(pp=2, n_micro=2)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(1))
        inner = optax.sgd(0.05, momentum=0.9)
        ids, tgt = _data(8, B=4)
        ref, states = dict(full), None
        for _ in range(3):
            ref, _, states = pp.reference_pipeline_step(
                CFG, plan, ref, [(ids, tgt)], inner, opt_states=states)
        chans, engines = _engines(2, 27280, monkeypatch)
        try:
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner) for e in engines]
            _run_world(pipes, [(ids, tgt)] * 2, steps=3)
            for pipe in pipes:
                lo, hi = pipe.stage_layers()
                want = pp.slice_stage_params(CFG, ref, lo, hi,
                                             pipe.stage == 0,
                                             pipe.stage == 1)
                assert _tree_equal(pipe.params[0], want)
        finally:
            for c in chans:
                c.close()

    def test_tp_within_stage_bitwise(self, monkeypatch):
        """tp=2 over each rank's LOCAL device pair (conftest forces 8
        virtual CPU devices): the Megatron column/row stage math under
        shard_map, bitwise vs the same-tp reference."""
        plan = ParallelPlan(pp=2, tp=2, n_micro=2)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(6))
        inner = optax.sgd(0.05)
        ids, tgt = _data(12, B=4)
        ref_full, _, _ = pp.reference_pipeline_step(
            CFG, plan, full, [(ids, tgt)], inner)
        devs = jax.devices()
        assert len(devs) >= 4, "conftest should force 8 CPU devices"
        chans, engines = _engines(2, 27340, monkeypatch)
        try:
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner,
                                     devices=devs[2 * i: 2 * i + 2])
                     for i, e in enumerate(engines)]
            _run_world(pipes, [(ids, tgt)] * 2)
            for pipe in pipes:
                lo, hi = pipe.stage_layers()
                want = pp.slice_stage_params(CFG, ref_full, lo, hi,
                                             pipe.stage == 0,
                                             pipe.stage == 1)
                assert _tree_equal(pipe.params[0], want)
        finally:
            for c in chans:
                c.close()

    def test_interleaved_bitwise(self, monkeypatch):
        plan = ParallelPlan(pp=2, n_micro=4, pp_schedule="interleaved",
                            interleave=2)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(2))
        inner = optax.sgd(0.05)
        ids, tgt = _data(9, B=8)
        ref_full, _, _ = pp.reference_pipeline_step(
            CFG, plan, full, [(ids, tgt)], inner)
        chans, engines = _engines(2, 27290, monkeypatch)
        try:
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner) for e in engines]
            _run_world(pipes, [(ids, tgt)] * 2)
            part = pp.interleaved_partition(CFG.n_layers, 2, 2)
            for pipe in pipes:
                for c in range(2):
                    lo, hi = part[pipe.stage][c]
                    vs = c * 2 + pipe.stage
                    want = pp.slice_stage_params(CFG, ref_full, lo, hi,
                                                 vs == 0, vs == 3)
                    assert _tree_equal(pipe.params[c], want)
        finally:
            for c in chans:
                c.close()


class TestZeroComposition:
    @pytest.mark.parametrize("zero,inner_fn", [
        (2, lambda: optax.sgd(0.05, momentum=0.9)),
        (2, lambda: optax.sgd(0.05)),
        (0, lambda: optax.sgd(0.05, momentum=0.9)),
    ])
    def test_pp_dp_bitwise(self, zero, inner_fn, monkeypatch):
        """pp=2 x dp=2 (the 2-slice 3D shape minus tp): the per-stage
        DP reduce-scatter buckets + chunked optimizer reproduce the
        replicated reference bitwise — with AND without momentum."""
        plan = ParallelPlan(pp=2, dp=2, n_micro=2, zero_stage=zero,
                            pp_schedule="1f1b")
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(3))
        inner = inner_fn()
        shards = [_data(10 + d, B=4) for d in range(2)]
        ref_full, ref_losses, _ = pp.reference_pipeline_step(
            CFG, plan, full, shards, inner_fn())
        chans, engines = _engines(4, 27300 + 20 * zero, monkeypatch)
        try:
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner_fn(), n_buckets=2)
                     for e in engines]
            outs = _run_world(
                pipes, [shards[i % 2] for i in range(4)])
            for i, loss in enumerate(outs):
                if pipes[i].stage == 1:
                    assert loss == pytest.approx(
                        float(np.mean(ref_losses[i % 2])), abs=1e-6)
            for pipe in pipes:
                lo, hi = pipe.stage_layers()
                want = pp.slice_stage_params(CFG, ref_full, lo, hi,
                                             pipe.stage == 0,
                                             pipe.stage == 1)
                assert _tree_equal(pipe.params[0], want)
        finally:
            for c in chans:
                c.close()


# -- elastic stage re-carve ---------------------------------------------------
def _commit_and_mirror(pipes, peers, boundary_cls=pp.StageBoundary):
    """Commit each rank's boundary + run the cross-stage ring mirror."""
    sbs = [boundary_cls() for _ in pipes]
    for pipe, sb in zip(pipes, sbs):
        pipe.commit_boundary(sb)

    def mirror(i):
        sbs[i].replicate_ring(peers[i].channel,
                              peers[i].cluster.workers,
                              tag=f"s{pipes[i].step_count}")

    run_all([lambda i=i: mirror(i) for i in range(len(pipes))])
    return sbs


class TestStageRecarve:
    def test_planned_merge_2_to_1(self, monkeypatch):
        """Planned 2-stage -> 1-stage merge (no deaths: the leaving
        stage serves its own spans): restored params + ZeRO momentum
        chunks are bitwise the merged originals."""
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils.envs import Config

        plan = ParallelPlan(pp=2, dp=1, n_micro=2, zero_stage=2)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(4))
        inner = optax.sgd(0.05, momentum=0.9)
        workers = PeerList.of(PeerID("127.0.0.1", 27400),
                              PeerID("127.0.0.1", 27401))
        runners = PeerList.parse("127.0.0.1:27499")
        cluster = Cluster(runners, workers)
        peers = [Peer(Config(self_id=w, cluster=cluster,
                             strategy=Strategy.STAR)) for w in workers]
        for p in peers:
            p.start()
        try:
            engines = [p.engine() for p in peers]
            pipes = [pp.HostPipeline(e, plan, CFG, full_params=full,
                                     inner=inner, peer=p)
                     for e, p in zip(engines, peers)]
            ids, tgt = _data(11, B=4)
            _run_world(pipes, [(ids, tgt)] * 2)
            sbs = _commit_and_mirror(pipes, peers)
            new_workers = workers.select([0])

            def carve(i):
                sbs[i].recarve(1, peer=peers[i], old_workers=workers,
                               new_workers=new_workers, tag="t")

            run_all([lambda i=i: carve(i) for i in range(2)])
            stage, n, params, opt = sbs[0].restore()
            assert (stage, n) == (0, 1)
            # params: the merged full tree, bitwise
            merged = pp.merge_stage_trees(
                CFG, 2, 1, [pipes[0].params[0], pipes[1].params[0]])
            assert _tree_equal(params, merged)
            # ZeRO momentum: unflatten each stage's trace chunk into
            # its param-shaped tree (dp=1: chunk == stage flat), merge
            # like params, re-flatten in the MERGED stage's layout —
            # bitwise against the re-carved chunk
            def unflatten_stage(lo, hi, first, last, flat):
                shapes = pp.stage_param_shapes(CFG, lo, hi, first, last)
                leaves, td = jax.tree_util.tree_flatten(shapes)
                out, off = [], 0
                for leaf in leaves:
                    sz = int(np.prod(leaf.shape)) if leaf.shape else 1
                    out.append(flat[off:off + sz].reshape(leaf.shape))
                    off += sz
                return jax.tree_util.tree_unflatten(td, out)

            smap = plan.stage_map(CFG.n_layers)
            tr_trees = []
            for i, pipe in enumerate(pipes):
                lo, hi = smap[i]
                t = np.asarray(jax.tree_util.tree_leaves(
                    pipe.opt_state[0])[0])[: pipe._flat_shapes[0]]
                tr_trees.append(unflatten_stage(lo, hi, i == 0, i == 1, t))
            merged_tr = pp.merge_stage_trees(CFG, 2, 1, tr_trees)
            want = np.concatenate(
                [np.asarray(l).ravel()
                 for l in jax.tree_util.tree_leaves(merged_tr)])
            got = np.asarray(jax.tree_util.tree_leaves(opt)[0])
            assert np.array_equal(got[: want.shape[0]], want)
            # a leaver dropped its shard
            with pytest.raises(ValueError, match="restore before"):
                sbs[1].restore()
        finally:
            for p in peers:
                try:
                    p.close()
                except Exception:  # noqa: BLE001
                    pass

    def test_partial_stage_death_rejected(self):
        sb = pp.StageBoundary()
        sb.commit(1, CFG, 0, 2, 2, 0,
                  pp.slice_stage_params(
                      CFG, pp.init_stacked_params(
                          CFG, jax.random.PRNGKey(0)), 0, 2, True, False),
                  optax.sgd(0.1).init(jnp.zeros((4,))), 2)
        with pytest.raises(ValueError, match="partially dead"):
            sb.recarve(1, dead=[2])

    def test_dead_buddy_unrecoverable(self):
        sb = pp.StageBoundary()
        sb.commit(1, CFG, 0, 4, 1, 0,
                  pp.slice_stage_params(
                      CFG, pp.init_stacked_params(
                          CFG, jax.random.PRNGKey(0)), 0, 1, True, False),
                  optax.sgd(0.1).init(jnp.zeros((4,))), 2)
        # stages 2 AND 3 dead: 3's buddy predecessor (2) is dead too —
        # mirror redundancy covers one failure domain, not two adjacent
        with pytest.raises(ValueError, match="buddy predecessor"):
            sb.recarve(2, dead=[2, 3])

    def test_missing_mirror_rejected(self):
        sb = pp.StageBoundary()
        sb.commit(1, CFG, 0, 2, 1, 0,
                  pp.slice_stage_params(
                      CFG, pp.init_stacked_params(
                          CFG, jax.random.PRNGKey(0)), 0, 2, True, False),
                  optax.sgd(0.1).init(jnp.zeros((4,))), 2)
        # stage 1 dead, this rank is its buddy predecessor but
        # replicate_ring was never run on this boundary
        with pytest.raises(ValueError, match="holds no mirror"):
            sb.recarve(1, dead=[1])

    def test_stale_mirror_step_rejected(self):
        """A mirror replicated at a DIFFERENT step than this boundary's
        commit must not serve a dead stage — it would blend optimizer
        states from two steps (the expect_step gate's failure mode, one
        hop removed)."""
        sb = pp.StageBoundary()
        sb.commit(5, CFG, 0, 2, 1, 0,
                  pp.slice_stage_params(
                      CFG, pp.init_stacked_params(
                          CFG, jax.random.PRNGKey(0)), 0, 2, True, False),
                  optax.sgd(0.1).init(jnp.zeros((4,))), 2)
        sb._buddy = {"pflat": np.zeros(4, np.float32),
                     "meta": np.array([4, 1, 2, 1, 0, 2], np.int64),
                     "vec": {}}
        sb._buddy_stage = 1
        with pytest.raises(ValueError, match="replicated at step 4"):
            sb.recarve(1, dead=[1])

    def test_step_gate(self):
        sb = pp.StageBoundary()
        sb.commit(5, CFG, 0, 1, 1, 0,
                  pp.slice_stage_params(
                      CFG, pp.init_stacked_params(
                          CFG, jax.random.PRNGKey(0)), 0, 4, True, True),
                  optax.sgd(0.1).init(jnp.zeros((4,))), 2)
        with pytest.raises(ValueError, match="replay from step"):
            sb.recarve(1, expect_step=4)

    def test_replicated_stateful_inner_rejected(self):
        sb = pp.StageBoundary()
        params = pp.slice_stage_params(
            CFG, pp.init_stacked_params(CFG, jax.random.PRNGKey(0)),
            0, 4, True, True)
        mom = optax.sgd(0.1, momentum=0.9).init(params)
        with pytest.raises(ValueError, match="ZeRO-2 flat-chunk"):
            sb.commit(1, CFG, 0, 1, 1, 0, params, mom, 0)


class TestChaosSliceLossRecarve:
    """THE acceptance run: a 2-slice emulated 3D world — PP across the
    DCN slices, TP=2 within each rank's local "ICI" device pair, ZeRO-2
    momentum on DP — trains through a chaos ``die_slice`` with ONE
    stage re-carve: the dead stage's params AND optimizer chunks
    restored from the predecessor slice's ring-buddy mirrors, and the
    post-loss world's final params bitwise a fixed-world replay from
    the same committed boundary."""

    @pytest.mark.slow  # ~60s: full slice-loss recarve + bitwise replay
    def test_die_slice_recarve_bitwise(self, monkeypatch):
        from tests.test_slices import make_slice_peers

        monkeypatch.setenv("KF_CHAOS_SPEC",
                           "die_slice:slice=1,step=2,mode=raise,rps=2")
        # wide enough to cover the step-0/1 jit compiles on a loaded
        # box, small enough to keep the post-kill detection bounded
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "12")
        workers, peers = make_slice_peers(4, 2, 27410, monkeypatch)
        plan = ParallelPlan(pp=2, dp=2, tp=2, n_micro=2, zero_stage=2)
        full = pp.init_stacked_params(CFG, jax.random.PRNGKey(5))
        mk_inner = lambda: optax.sgd(0.05, momentum=0.9)  # noqa: E731
        shards = [_data(20 + d, B=4) for d in range(2)]
        results = [None] * 4
        recarves = []
        devs = jax.devices()
        assert len(devs) >= 8, "conftest should force 8 CPU devices"

        def worker(i):
            # rank i's local "ICI" = its own device pair: TP never
            # crosses a slice
            pipe = pp.HostPipeline(peers[i].engine(), plan, CFG,
                                   full_params=full, inner=mk_inner(),
                                   peer=peers[i],
                                   devices=devs[2 * i: 2 * i + 2])
            sb = pp.StageBoundary()
            snap = StepSnapshot()
            ids, tgt = shards[i % 2]
            try:
                # compile locally FIRST: a cold tp-shard_map jit inside
                # the first recv window would read as a dead peer
                pipe.warmup(ids.shape[0], ids.shape[1])
                # steps 0 and 1 train clean; commit + mirror the
                # step-2 boundary
                for s in (0, 1):
                    chaos.note_step(peers[i].chaos_rank(), s)
                    pipe.train_step(ids, tgt)
                pipe.commit_boundary(sb)
                sb.replicate_ring(peers[i].channel,
                                  peers[i].cluster.workers, tag="b2")
                snap.commit(2, {"anchor": np.int64(2)})
                # step 2: slice 1 dies at the boundary
                chaos.note_step(peers[i].chaos_rank(), 2)
                pipe.train_step(ids, tgt)
                results[i] = ("no-death", None)
            except chaos.InjectedDeath:
                peers[i].close()
                results[i] = ("died", None)
            except PeerFailureError as err:
                shrunk, replay = peers[i].recover_from_failure(
                    err, snapshot=snap, stage_boundary=sb)
                assert shrunk and replay is not None and replay[0] == 2
                recarves.append(i)
                new_plan = plan.with_stages(1)
                pipe2 = pp.HostPipeline.from_boundary(
                    peers[i].engine(), new_plan, CFG, sb,
                    inner=mk_inner(), peer=peers[i],
                    devices=devs[2 * i: 2 * i + 2])
                assert pipe2.stage_layers() == (0, CFG.n_layers)
                pipe2.warmup(ids.shape[0], ids.shape[1])
                pipe2.train_step(ids, tgt)
                results[i] = ("recovered", pipe2)

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(4)]
        try:
            for t in ts:
                t.start()
            for t in ts:
                t.join(300)
            assert not any(t.is_alive() for t in ts), "recovery hung"
            assert results[2][0] == "died" and results[3][0] == "died"
            assert results[0][0] == "recovered"
            assert results[1][0] == "recovered"
            assert sorted(recarves) == [0, 1], \
                "exactly one re-carve per survivor"

            # fixed-world replay: steps 0-1 on the 2-stage world, then
            # the survivor step on a 1-stage dp=2 world from the SAME
            # boundary (merged params + merged momentum)
            full1, states1 = dict(full), None
            for _ in range(2):
                full1, _, states1 = pp.reference_pipeline_step(
                    CFG, plan, full1, shards, mk_inner(),
                    opt_states=states1)
            merged_trace = pp.merge_stage_trees(
                CFG, 2, 1,
                [states1[0][0].trace, states1[1][0].trace])
            merged_state = (optax.TraceState(trace=merged_trace),
                            states1[0][1])
            plan1 = plan.with_stages(1)
            full2, _, _ = pp.reference_pipeline_step(
                CFG, plan1, full1, shards, mk_inner(),
                opt_states=[merged_state])
            for i in (0, 1):
                pipe2 = results[i][1]
                want = pp.slice_stage_params(CFG, full2, 0, CFG.n_layers,
                                             True, True)
                assert _tree_equal(pipe2.params[0], want), \
                    "post-re-carve step diverged from fixed-world replay"
        finally:
            for p in peers:
                try:
                    p.close()
                except Exception:  # noqa: BLE001
                    pass


# -- xray bubble phase --------------------------------------------------------
class TestXrayBubblePhase:
    def test_pp_bubble_is_a_distinct_phase(self):
        from kungfu_tpu.monitor import xray

        t0 = 1000.0
        evs = [
            {"ts": t0, "rank": 0, "step": 1, "kind": "pp",
             "name": "bubble", "dur": 0.2, "attrs": {"stage": 1}},
            {"ts": t0 + 0.2, "rank": 0, "step": 1, "kind": "pp",
             "name": "fwd", "dur": 0.3, "attrs": {"stage": 1}},
            {"ts": t0 + 0.5, "rank": 0, "step": 1, "kind": "collective",
             "name": "engine.all_reduce", "dur": 0.1,
             "attrs": {"tag": "g1", "op": "all_reduce"}},
        ]
        split = xray.rank_phase_split(evs)
        assert split["pp_bubble"] == pytest.approx(0.2)
        assert split["comm_exposed"] == pytest.approx(0.1)
        # fwd/bwd pp spans are stage COMPUTE, not a separate phase
        assert split["compute"] == pytest.approx(0.3)
        assert "pp_bubble" in xray.PHASES
        assert "pp" in xray.XRAY_KINDS

    def test_report_kinds_still_superset(self):
        from kungfu_tpu.monitor import xray
        from kungfu_tpu.monitor.aggregator import REPORT_KINDS

        assert xray.XRAY_KINDS <= REPORT_KINDS


# -- serve autoscale execution ------------------------------------------------
class _StubSlicePeer:
    def __init__(self, workers):
        from types import SimpleNamespace

        self.config = SimpleNamespace(
            cluster=SimpleNamespace(workers=workers),
            self_id=workers[4], config_server="")

    def slice_topology(self):
        # no MEMBERSHIP alignment (single-slice peer); the ROUTER still
        # excludes at slice grain via its explicit topology — the
        # combination under test is the exclusion grain, not alignment
        return None

    def chaos_rank(self):
        return 4

    def rank(self):
        return 4


class _StubSliceRouter:
    """Duck-typed slice-aware router: mark_worker_dead excludes the
    whole slice, like the real fault ladder."""

    def __init__(self, peer, live):
        from kungfu_tpu.elastic.slices import SliceTopology

        self.peer = peer
        self.topology = SliceTopology(2, 2)
        self._live = set(live)
        self.busy: set = set()
        self.replays = 0

    @property
    def live_workers(self):
        return sorted(self._live)

    def outstanding(self, r):
        return 1 if r in self.busy else 0

    def mark_worker_dead(self, r, readmit=True):
        s = self.topology.slice_of(r)
        ex = [x for x in self.topology.ranks_in(s) if x in self._live]
        if any(x in self.busy for x in ex):
            self.replays += 1  # a busy sibling got swept = replay storm
        self._live -= set(ex)
        return ex

    def admit_worker(self, r):
        self._live.add(r)
        return True


class TestServeFleetSliceScaleIn:
    def test_retires_whole_drained_slices_only(self):
        """Scale-in on a slice-aware router retires whole DRAINED
        slices: a slice with a busy member is skipped entirely —
        retiring its drained sibling would cascade-exclude the busy one
        through the slice-grain fault ladder and replay its requests."""
        from kungfu_tpu.serve.scale import ServeFleet

        workers = PeerList.of(
            *(PeerID("127.0.0.1", 27470 + i) for i in range(5)))
        peer = _StubSlicePeer(workers)
        router = _StubSliceRouter(peer, live=[0, 1, 2, 3])
        router.busy = {2}

        class _W:
            dead = False

            def stop(self):
                self.dead = True

        fleet = ServeFleet(router, None, lambda r: _W(),
                           plan=ParallelPlan(dp=2))
        fleet.workers = {r: _W() for r in (0, 1, 2, 3)}
        fleet.scale_to(2)
        # slice 1 (ranks 2,3) has a busy member: skipped whole; slice 0
        # is drained and fleet-owned: retired whole
        assert router.live_workers == [2, 3]
        assert router.replays == 0, "a busy sibling was swept"
        assert 0 not in fleet.workers and 1 not in fleet.workers
        assert all(fleet.workers[r].dead is False for r in (2, 3))
        # every group busy -> nothing retires
        router2 = _StubSliceRouter(peer, live=[0, 1, 2, 3])
        router2.busy = {1, 2}
        fleet2 = ServeFleet(router2, None, lambda r: _W(),
                            plan=ParallelPlan(dp=2))
        fleet2.workers = {r: _W() for r in (0, 1, 2, 3)}
        fleet2.scale_to(2)
        assert router2.live_workers == [0, 1, 2, 3]
        assert router2.replays == 0


class TestServeFleetAutoscale:
    def test_intent_spawns_real_worker(self, monkeypatch):
        """Queue pressure + blown SLO raises a +1 intent; the fleet
        executes it as a REAL spawn: a new engine + ServeWorker on a
        provisioned rank, admitted to the router, and serving traffic."""
        from kungfu_tpu.models.transformer import Transformer
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.policy.serve import ServeAutoscalePolicy
        from kungfu_tpu.serve.engine import InferenceEngine
        from kungfu_tpu.serve.router import ServeRouter, ServeWorker
        from kungfu_tpu.serve.scale import ServeFleet
        from kungfu_tpu.serve.slo import SLOTargets
        from kungfu_tpu.utils.envs import Config

        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        monkeypatch.setenv("KF_NATIVE_ENGINE", "0")
        cfg = TransformerConfig(vocab_size=64, d_model=16, n_layers=2,
                                n_heads=2, d_ff=32, max_seq=32,
                                dtype="float32")
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        workers = PeerList.of(
            *(PeerID("127.0.0.1", 27450 + i) for i in range(3)))
        cluster = Cluster(PeerList.parse("127.0.0.1:27459"), workers)
        peers = [Peer(Config(self_id=w, cluster=cluster,
                             strategy=Strategy.STAR)) for w in workers]
        for p in peers:
            p.start()
        spawned = {}

        def spawn(rank):
            eng = InferenceEngine(model, params, max_batch=2, rank=rank,
                                  plan=ParallelPlan(dp=1))
            eng.warmup(prompt_lens=(4,))
            w = ServeWorker(peers[rank], eng, commit_every=2).start()
            spawned[rank] = w
            return w

        try:
            router = ServeRouter(peers[2], worker_ranks=[0],
                                 queue_depth=8, deadline_s=10.0)
            first = spawn(0)
            fleet = ServeFleet(
                router,
                ServeAutoscalePolicy(
                    targets=SLOTargets(ttft_s=0.5, e2e_s=1.0),
                    scale_up_queue=2, cooldown_steps=0),
                spawn, plan=ParallelPlan(dp=1))
            assert fleet.live() == [0]
            # pressure + blown SLO -> +1 intent -> a real spawn
            got = fleet.tick(serve_queued=4, serve_e2e_ms=5000.0)
            assert got == [1]
            assert router.live_workers == [0, 1]
            assert 1 in spawned and not spawned[1].dead
            # the new worker actually serves
            h = router.submit([1, 2, 3], 8)
            toks = h.wait(timeout=30)
            assert len(toks) > 0
            # idle + wide margin -> scale back down to the plan floor
            got = fleet.tick(serve_queued=0, serve_e2e_ms=10.0)
            assert got == []
            assert router.live_workers == [0]
        finally:
            for w in spawned.values():
                if not w.dead:
                    w.stop()
            if first and not first.dead:
                first.stop()
            try:
                router.close()
            except Exception:  # noqa: BLE001
                pass
            for p in peers:
                try:
                    p.close()
                except Exception:  # noqa: BLE001
                    pass
