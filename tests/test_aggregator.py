"""Live cluster plane tests: snapshot schema, aggregator staleness +
online skew, the config-server mounting, ``kftop``, and the offline
(kftrace) vs online (aggregator) skew agreement."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from kungfu_tpu.monitor import timeline, traceview
from kungfu_tpu.monitor.aggregator import (
    ClusterAggregator,
    RankReporter,
    SNAPSHOT_FIELDS,
    VIEW_FIELDS,
    control_event,
    field,
    make_snapshot,
    post_control,
    push_period_from_env,
    server_base,
    stale_after_from_env,
)
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils import trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _span(rank, step, dur, tag, ts=None):
    return {"ts": time.time() if ts is None else ts, "rank": rank,
            "step": step, "kind": "collective", "name": "engine.all_reduce",
            "dur": dur, "attrs": {"op": "all_reduce", "tag": tag}}


class TestSchema:
    def test_make_snapshot_rejects_unknown_field(self):
        with pytest.raises(ValueError, match="stepp"):
            make_snapshot(rank=0, stepp=1)

    def test_make_snapshot_stamps_wire_version(self):
        snap = make_snapshot(rank=3, step=7)
        assert snap["kfmon"] == 1
        assert field(snap, "rank") == 3 and field(snap, "step") == 7

    def test_view_fields_cover_snapshot_row_fields(self):
        # every per-rank row field kftop renders must be declared
        assert {"rank", "step", "step_time_s", "age_s", "counters",
                "net", "strategy"} <= VIEW_FIELDS
        assert "events" in SNAPSHOT_FIELDS  # the skew feedstock

    def test_server_base(self):
        assert server_base("http://h:9100/get") == "http://h:9100"
        assert server_base("http://h:9100") == "http://h:9100"
        assert server_base("h:9100") == "http://h:9100"

    def test_env_defaults(self, monkeypatch):
        monkeypatch.delenv("KF_CONFIG_MONITOR_PUSH_PERIOD", raising=False)
        monkeypatch.delenv("KF_CONFIG_MONITOR_STALE_AFTER", raising=False)
        assert push_period_from_env() == 1.0
        assert stale_after_from_env() == 3.0
        monkeypatch.setenv("KF_CONFIG_MONITOR_PUSH_PERIOD", "0.5")
        assert push_period_from_env() == 0.5
        assert stale_after_from_env() == 1.5  # 3 push periods
        monkeypatch.setenv("KF_CONFIG_MONITOR_STALE_AFTER", "7")
        assert stale_after_from_env() == 7.0  # absolute override wins


class TestSkewDeterminism:
    def test_tie_breaks_independent_of_event_order(self):
        """The shared-math guarantee would be vacuous if arrival order
        (offline: time-sorted; online: push order) could flip a tie."""
        import itertools

        from kungfu_tpu.monitor import skew as skewlib

        evs = [_span(r, 1, 0.01 if r < 2 else 0.1, "g") for r in range(3)]
        rows0 = skewlib.skew_rows(evs)
        assert rows0[0]["fastest_rank"] == 0  # tie with rank 1 → lowest
        for perm in itertools.permutations(evs):
            assert skewlib.skew_rows(list(perm)) == rows0
            assert skewlib.straggler_verdict(list(perm)) == 2


class TestAggregator:
    def _agg(self, stale_after=1.0):
        clock = [1000.0]
        agg = ClusterAggregator(stale_after=stale_after,
                                time_fn=lambda: clock[0])
        return agg, clock

    def test_ingest_rejects_garbage(self):
        agg, _ = self._agg()
        with pytest.raises(ValueError):
            agg.ingest({"hello": 1})
        with pytest.raises(ValueError):
            agg.ingest(make_snapshot(step=1))  # no rank
        with pytest.raises(ValueError):
            agg.ingest({"kfmon": 1, "rank": 0, "bogus_field": 1})

    def test_staleness_clock(self):
        agg, clock = self._agg(stale_after=1.0)
        agg.ingest(make_snapshot(rank=0, step=1))
        agg.ingest(make_snapshot(rank=1, step=1))
        assert agg.stale_ranks() == []
        clock[0] += 0.5
        agg.ingest(make_snapshot(rank=0, step=2))  # rank 0 refreshes
        clock[0] += 0.7                            # rank 1 now 1.2s old
        assert agg.stale_ranks() == [1]
        view = agg.cluster_view()
        assert field(view, "stale") == [1]
        rows = {field(r, "rank"): r for r in field(view, "ranks")}
        assert rows[1]["stale"] and not rows[0]["stale"]
        assert rows[0]["step"] == 2

    def test_online_skew_names_planted_rank(self):
        agg, _ = self._agg()
        for rank in range(3):
            dur = 0.2 if rank == 2 else 0.02
            agg.ingest(make_snapshot(
                rank=rank, step=1, events=[_span(rank, 1, dur, "g1")]))
        view = agg.cluster_view()
        assert field(view, "straggler") == 2
        row = field(view, "skew")[0]
        assert field(row, "slowest_rank") == 2
        assert field(row, "skew_s") == pytest.approx(0.18)

    def test_rankless_events_get_stamped(self):
        agg, _ = self._agg()
        ev = _span(None, 1, 0.1, "g1")
        agg.ingest(make_snapshot(rank=5, step=1, events=[ev]))
        agg.ingest(make_snapshot(rank=6, step=1,
                                 events=[_span(6, 1, 0.01, "g1")]))
        assert field(agg.cluster_view(), "skew")[0]["slowest_rank"] == 5

    def test_shrink_control_evicts_dead_rank_state(self):
        """A dead rank's last spans must not feed the skew verdict
        forever: the shrink control event (which names the dead set)
        evicts its window and row."""
        agg, _ = self._agg()
        for rank in range(3):
            dur = 0.2 if rank == 2 else 0.02
            agg.ingest(make_snapshot(
                rank=rank, step=1, events=[_span(rank, 1, dur, "g1")]))
        assert field(agg.cluster_view(), "straggler") == 2
        agg.ingest(control_event("shrink", rank=0, dead=[2], version=2))
        view = agg.cluster_view()
        assert 2 not in [field(r, "rank") for r in field(view, "ranks")]
        assert field(view, "straggler") != 2
        assert field(view, "stale") == []  # the dead rank can't sit stale

    def test_control_events_and_quorum_margin(self):
        agg, _ = self._agg()
        agg.ingest(control_event("shrink", rank=0, dead=[3], version=9))
        view = agg.cluster_view({"version": 9, "size": 5, "workers": []})
        cluster = field(view, "cluster")
        assert field(cluster, "quorum_margin") == 2  # 5 -> 3 still majority
        assert field(field(cluster, "last_control"), "kind") == "shrink"
        assert field(view, "controls")[-1]["attrs"]["dead"] == [3]

    def test_prometheus_render(self):
        agg, clock = self._agg(stale_after=1.0)
        agg.ingest(make_snapshot(rank=0, step=4, step_time_s=0.5,
                                 events=[_span(0, 4, 0.1, "g")]))
        agg.ingest(make_snapshot(rank=1, step=4,
                                 events=[_span(1, 4, 0.01, "g")]))
        clock[0] += 2.0
        text = agg.render_prometheus({"version": 7, "size": 2, "workers": []})
        assert "kf_cluster_ranks 2" in text
        assert "kf_cluster_stale_ranks 2" in text
        assert "kf_cluster_config_version 7" in text
        assert 'kf_cluster_rank_step{rank="0"} 4' in text
        assert 'kf_cluster_skew_seconds{op="all_reduce",tag="g"}' in text
        assert "# TYPE kf_cluster_ranks gauge" in text


class TestReporter:
    @pytest.fixture(autouse=True)
    def _clean(self, monkeypatch):
        monkeypatch.delenv(trace.ENABLE_TRACE, raising=False)
        timeline.reset()
        timeline.set_rank(None)
        yield
        timeline.reset()
        timeline.set_rank(None)

    def test_snapshot_contents_and_incremental_events(self):
        rep = RankReporter(2, "http://127.0.0.1:1/get", period=0.1)
        timeline.set_step(11)
        with timeline.span("collective", "engine.all_reduce", rank=2,
                           force=True, op="all_reduce", tag="t0"):
            pass
        snap = rep.snapshot_once()
        assert field(snap, "rank") == 2 and field(snap, "step") == 11
        evs = field(snap, "events")
        assert [e["attrs"]["tag"] for e in evs] == ["t0"]
        # a collective span also lands in the latency histogram deltas
        assert any("kf_collective_latency_seconds" in k
                   for k in field(snap, "latency"))
        # second snapshot: cursor advanced, nothing re-sent
        assert field(rep.snapshot_once(), "events") == []
        timeline.event("mark", "not-reported", force=True)  # not a REPORT_KIND
        timeline.event("chaos", "delay", rank=2, force=True)
        evs = field(rep.snapshot_once(), "events")
        assert [e["kind"] for e in evs] == ["chaos"]

    def test_step_time_ema(self):
        rep = RankReporter(0, "http://127.0.0.1:1", period=0.1)
        now = 100.0
        assert rep._step_time(5, now) is None        # first sight: no rate
        assert rep._step_time(7, now + 1.0) == pytest.approx(0.5)
        # EMA pulls toward the new 1.0 s/step sample
        second = rep._step_time(8, now + 2.0)
        assert 0.5 < second < 1.0

    def test_push_failure_is_swallowed(self):
        rep = RankReporter(0, "http://127.0.0.1:9/get", period=0.1)
        assert rep.push_once() is False  # nothing listening: no raise

    def test_failed_push_carries_window_to_next_snapshot(self):
        """Collection advances the cursor/delta baselines, so an
        undelivered window must ride along to the next push — a config-
        server blip during an incident must not hole the skew window."""
        rep = RankReporter(0, "http://127.0.0.1:9/get", period=0.1)
        with timeline.span("collective", "engine.all_reduce", rank=0,
                           force=True, op="all_reduce", tag="carried"):
            pass
        assert rep.push_once() is False  # nothing listening
        snap = rep.snapshot_once()
        assert [e["attrs"]["tag"] for e in field(snap, "events")] \
            == ["carried"]
        assert any("kf_collective_latency_seconds" in k
                   for k in field(snap, "latency"))


@pytest.fixture
def live_cluster():
    """ConfigServer + aggregator on an ephemeral port, with a stored
    3-worker cluster — the co-hosting layout `kfrun -monitor` builds."""
    from kungfu_tpu.elastic.configserver import ConfigServer
    from kungfu_tpu.plan import Cluster, PeerList

    workers = PeerList.parse("127.0.0.1:27411,127.0.0.1:27412,127.0.0.1:27413")
    cluster = Cluster(PeerList.parse("127.0.0.1:38091"), workers)
    agg = ClusterAggregator(stale_after=0.45)
    srv = ConfigServer(port=0, cluster=cluster, aggregator=agg).start()
    yield srv, agg, f"http://127.0.0.1:{srv.port}/get"
    srv.stop()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


class TestLiveCluster:
    """The acceptance path: a 3-rank in-process cluster with a planted
    slow rank, observed online through ``/cluster``."""

    PERIOD = 0.15

    def _reporters(self, url, events):
        return [
            RankReporter(r, url, period=self.PERIOD,
                         events_fn=lambda r=r: events.pop(r, []))
            for r in range(3)
        ]

    def _planted_events(self):
        """Rank 2 is ~10x slower on every tag; distinct skews per tag so
        row order is deterministic for the offline/online comparison."""
        events = {}
        for rank in range(3):
            evs = []
            for step in range(3):
                dur = (0.10 + 0.01 * step) if rank == 2 else 0.01
                evs.append(_span(rank, step, dur, f"grad{step}",
                                 ts=100.0 + step + 0.01 * rank))
            events[rank] = evs
        return events

    def test_cluster_names_slow_rank_within_push_interval(self, live_cluster):
        srv, _, url = live_cluster
        events = self._planted_events()
        offline = [list(v) for v in events.values()]  # copy before pop
        reps = self._reporters(url, events)
        for rp in reps:
            rp.start()
        try:
            deadline = time.time() + 10 * self.PERIOD
            view = None
            while time.time() < deadline:
                view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
                if len(field(view, "skew")) >= 3:
                    break
                time.sleep(self.PERIOD / 3)
            assert view is not None and len(field(view, "skew")) >= 3
            assert field(view, "straggler") == 2
            for row in field(view, "skew"):
                assert field(row, "slowest_rank") == 2
            # per-step windows also finger rank 2
            for w in field(view, "slowest_per_step"):
                assert w["slowest_rank"] == 2
            # cluster health from the co-hosted config store
            cluster = field(view, "cluster")
            assert field(cluster, "size") == 3
            assert field(cluster, "quorum_margin") == 1

            # -- offline/online agreement: kftrace over dumps of the SAME
            # events must produce byte-identical skew rows (shared
            # monitor/skew.py math is the guarantee under test)
            import tempfile

            dumps = []
            with tempfile.TemporaryDirectory() as td:
                for rank, evs in enumerate(offline):
                    p = os.path.join(td, f"trace-r{rank}.jsonl")
                    with open(p, "w") as f:
                        f.write(json.dumps(
                            {"kftrace": 1, "rank": rank, "pid": rank,
                             "dropped": 0, "wall": 0.0}) + "\n")
                        for ev in evs:
                            f.write(json.dumps(ev) + "\n")
                    dumps.append(p)
                offline_rows = traceview.skew_rows(traceview.load_all(dumps))
            assert offline_rows == field(view, "skew")
        finally:
            for rp in reps:
                rp.stop()

    def test_dead_rank_goes_stale_before_detector_window(self, live_cluster):
        """A rank whose pushes stop (the observable effect of a chaos
        ``die`` on that process) flips to *stale* on the aggregator's
        clock — which sits far inside the failure detector's 10 s down
        verdict, so kftop shows the problem first."""
        from kungfu_tpu.monitor.detector import DEFAULT_STALL_TIMEOUT_S

        srv, agg, url = live_cluster
        assert agg.stale_after < DEFAULT_STALL_TIMEOUT_S / 10
        reps = self._reporters(url, {})
        for rp in reps:
            rp.start()
        try:
            time.sleep(2.5 * self.PERIOD)
            view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
            assert field(view, "stale") == []
            reps[1].stop()  # rank 1 "dies": its snapshots stop arriving
            killed = time.time()
            deadline = killed + 2 * agg.stale_after + 1.0
            stale = []
            while time.time() < deadline:
                view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
                stale = field(view, "stale")
                if stale:
                    break
                time.sleep(0.05)
            assert stale == [1]
            # flagged well before a detector could have ruled it down
            assert time.time() - killed < DEFAULT_STALL_TIMEOUT_S
        finally:
            for rp in reps:
                rp.stop()

    def test_control_event_round_trip(self, live_cluster):
        srv, _, url = live_cluster
        assert post_control(url, "resize", rank=0, version=4, size=2)
        view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
        last = field(field(view, "cluster"), "last_control")
        assert field(last, "kind") == "resize"
        assert field(last, "attrs") == {"version": 4, "size": 2}

    def test_metrics_endpoint_merged(self, live_cluster):
        srv, _, url = live_cluster
        rep = RankReporter(0, url, period=self.PERIOD)
        rep.push_once()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as resp:
            text = resp.read().decode()
        assert "kf_cluster_ranks 1" in text
        assert "kf_cluster_config_version 0" in text

    def test_push_rejects_malformed(self, live_cluster):
        srv, _, _ = live_cluster
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/push", data=b'{"bogus": 1}',
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400

    def test_post_control_if_enabled_gates_on_env(self, monkeypatch,
                                                  live_cluster):
        from kungfu_tpu.monitor.aggregator import post_control_if_enabled

        srv, agg, url = live_cluster

        class ShimConfig:
            config_server = url

        class ShimPeer:
            config = ShimConfig()

            @staticmethod
            def chaos_rank():
                return 0

        monkeypatch.delenv("KF_CONFIG_ENABLE_CLUSTER_MONITOR", raising=False)
        assert post_control_if_enabled(ShimPeer, "resize", version=1) is False
        monkeypatch.setenv("KF_CONFIG_ENABLE_CLUSTER_MONITOR", "1")
        assert post_control_if_enabled(ShimPeer, "resize", version=1) is True
        view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
        assert field(field(field(view, "cluster"), "last_control"),
                     "kind") == "resize"

    def test_config_routes_still_work(self, live_cluster):
        srv, _, _ = live_cluster
        got = _get_json(f"http://127.0.0.1:{srv.port}/get")
        assert got["version"] == 0 and "cluster" in got


class TestKftop:
    def test_render_view_marks_stale_and_skew(self):
        from kungfu_tpu.monitor.kftop import render_view

        clock = [50.0]
        agg = ClusterAggregator(stale_after=1.0, time_fn=lambda: clock[0])
        agg.ingest(make_snapshot(
            rank=0, step=9, step_time_s=0.3,
            counters={"kf_engine_retries_total": 4},
            events=[_span(0, 9, 0.2, "g9", ts=49.0)],
            net={"egress_bytes": 5 << 20, "ingress_bytes": 0},
            strategy="RING"))
        agg.ingest(make_snapshot(rank=1, step=9,
                                 events=[_span(1, 9, 0.01, "g9", ts=49.0)]))
        clock[0] += 0.5
        # pushes are complete snapshots — the latest one replaces the row
        agg.ingest(make_snapshot(
            rank=0, step=10, step_time_s=0.3,
            counters={"kf_engine_retries_total": 4},
            net={"egress_bytes": 5 << 20, "ingress_bytes": 0},
            strategy="RING"))
        clock[0] += 0.7  # rank 1 now stale
        text = render_view(agg.cluster_view({"version": 3, "size": 2,
                                             "workers": []}))
        assert "STALE" in text and "straggler: rank 0" in text
        assert "all_reduce/g9" in text
        assert "cluster v3" in text
        assert "RING" in text and "5.0MiB" in text

    def test_json_mode_against_live_server(self, live_cluster, capsys):
        from kungfu_tpu.monitor.kftop import main

        srv, _, url = live_cluster
        RankReporter(0, url, period=0.1).push_once()
        assert main(["--json", "--server", url]) == 0
        out = json.loads(capsys.readouterr().out)
        assert [field(r, "rank") for r in field(out, "ranks")] == [0]
        assert main(["--once", "--server", url]) == 0
        assert "kfmon @" in capsys.readouterr().out

    def test_unreachable_server_exits_nonzero(self, capsys):
        from kungfu_tpu.monitor.kftop import main

        assert main(["--json", "--server", "http://127.0.0.1:9/get"]) == 1

    def test_script_self_check(self):
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kftop"),
             "--self-check"],
            capture_output=True, timeout=60,
        )
        assert rc.returncode == 0, rc.stdout.decode() + rc.stderr.decode()


class TestPeerWiring:
    def test_peer_starts_and_stops_reporter(self, monkeypatch, live_cluster):
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.utils.envs import Config

        srv, agg, url = live_cluster
        monkeypatch.setenv("KF_CONFIG_ENABLE_CLUSTER_MONITOR", "1")
        monkeypatch.setenv("KF_CONFIG_MONITOR_PUSH_PERIOD", "0.1")
        workers = PeerList.parse("127.0.0.1:27421,127.0.0.1:27422")
        cluster = Cluster(PeerList.parse("127.0.0.1:38092"), workers)
        peers = [Peer(Config(self_id=w, cluster=cluster, config_server=url))
                 for w in workers]
        for p in peers:
            p.start()
        try:
            assert all(p._reporter is not None for p in peers)
            deadline = time.time() + 5
            while time.time() < deadline:
                view = _get_json(f"http://127.0.0.1:{srv.port}/cluster")
                if len(field(view, "ranks")) == 2:
                    break
                time.sleep(0.05)
            assert [field(r, "rank") for r in field(view, "ranks")] == [0, 1]
            # the engine strategy lands on the snapshot
            assert all(field(r, "strategy") for r in field(view, "ranks"))
        finally:
            for p in peers:
                p.close()
        assert all(p._reporter is None for p in peers)


class TestOptStateBytesGauge:
    """The ZeRO memory column: kf_opt_state_bytes set by
    record_opt_state_gauge must ride a reporter snapshot into the
    aggregator's per-rank view (kftop / /metrics see it live)."""

    def test_gauge_flows_through_snapshot(self):
        from kungfu_tpu.parallel.zero import record_opt_state_gauge

        nbytes = record_opt_state_gauge(
            {"mu": __import__("numpy").zeros(1024, dtype="float32")})
        assert nbytes == 4096
        rep = RankReporter(3, "http://127.0.0.1:1/push", period=0.1)
        snap = rep.snapshot_once()
        assert field(snap, "gauges")["kf_opt_state_bytes"] == 4096.0

        agg = ClusterAggregator(stale_after=10.0)
        agg.ingest(snap)
        rows = {field(r, "rank"): r for r in
                field(agg.cluster_view(), "ranks")}
        assert rows[3]["gauges"]["kf_opt_state_bytes"] == 4096.0
