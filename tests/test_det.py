"""kf-det in tier-1: the replay-determinism rules must catch what they
claim to catch (fixtures under tests/lint_fixtures/ seed known
violations), stay quiet on the sanctioned idioms, and flip red on the
acceptance mutations applied to copies of the real tree."""

import os
import shutil
import subprocess
import sys

from kungfu_tpu.analysis import core, detrules, taint
from kungfu_tpu.analysis.cli import (
    CHECKERS,
    DET_CHECKERS,
    expand_coupled,
    main as cli_main,
)
from kungfu_tpu.analysis.core import repo_root

ROOT = repo_root(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")


def _tmp_tree(tmp_path, files):
    """Build a minimal repo layout: {relpath: source or fixture name}."""
    for rel, content in files.items():
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        if os.path.exists(os.path.join(FIXTURES, str(content))):
            shutil.copy(os.path.join(FIXTURES, str(content)), dst)
        else:
            dst.write_text(content)
    return str(tmp_path)


def _det_check_all(root):
    out = []
    out.extend(detrules.check_replay_taint(root))
    out.extend(detrules.check_rng_discipline(root))
    out.extend(detrules.check_reduction_order(root))
    return out


class TestDetRegistration:
    def test_det_checkers_registered(self):
        assert set(DET_CHECKERS) == {
            "replay-taint", "rng-discipline", "reduction-order"}
        assert set(DET_CHECKERS) <= set(CHECKERS)

    def test_cli_lists_det_rules(self, capsys):
        assert cli_main(["--list"]) == 0
        listed = capsys.readouterr().out.split()
        for name in DET_CHECKERS:
            assert name in listed


class TestReplayTaint:
    """The tentpole: entropy sources to replay-critical sinks, at
    interprocedural depth, with sanitizer awareness."""

    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        got = detrules.check_replay_taint(root)
        assert {v.line for v in got} == {20, 32, 42, 50, 58, 68}, \
            [v.render() for v in got]

    def test_two_calls_deep_chain_rendered(self, tmp_path):
        """The source->sink call path is part of the finding: time.time()
        inside _stamp, through _token, into the consensus payload."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        msg = {v.line: v.message for v in
               detrules.check_replay_taint(root)}[20]
        assert "time.time()" in msg
        assert "returned through _stamp()" in msg
        assert "returned through _token()" in msg
        assert "consensus" in msg

    def test_param_flow_through_helper(self, tmp_path):
        """uuid4 rides a pure formatter's param->return flow into a
        rendezvous tag name."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        msg = {v.line: v.message for v in
               detrules.check_replay_taint(root)}[32]
        assert "uuid4()" in msg and "name=" in msg

    def test_branch_sanitizer_does_not_launder(self, tmp_path):
        """A clean value on ONE branch must not launder the tainted
        other branch (env forks are merged by union)."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        lines = {v.line for v in detrules.check_replay_taint(root)}
        assert 42 in lines   # branch_sanitizer's barrier
        assert 68 in lines   # agree_one_branch's install consensus

    def test_container_round_trips_tracked(self, tmp_path):
        """Entropy stored into a dict/list survives serialization into
        the sink payload (weak container updates)."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        lines = {v.line for v in detrules.check_replay_taint(root)}
        assert {50, 58} <= lines

    def test_suppression_honored(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        assert all(v.line != 73 for v in detrules.check_replay_taint(root))

    def test_sanctioned_flows_clean(self, tmp_path):
        """Agreed digests, agreement-op round trips, sorted() tags, and
        local-only gauges are the sanctioned idioms — zero findings."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_good.py": "taint_good.py"})
        got = detrules.check_replay_taint(root)
        assert got == [], [v.render() for v in got]


class TestRngDiscipline:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_bad.py": "rng_bad.py"})
        got = detrules.check_rng_discipline(root)
        assert {v.line for v in got} == {14, 21, 28, 33, 38, 45}, \
            [v.render() for v in got]

    def test_split_reuse_names_the_key(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_bad.py": "rng_bad.py"})
        msgs = {v.line: v.message for v in
                detrules.check_rng_discipline(root)}
        assert "`key` reused" in msgs[14]
        assert "split again" in msgs[21]

    def test_fold_in_entropy_carries_source(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_bad.py": "rng_bad.py"})
        msgs = {v.line: v.message for v in
                detrules.check_rng_discipline(root)}
        assert "fold_in" in msgs[28] and "time.time()" in msgs[28]
        assert "getpid()" in msgs[33]
        assert "time_ns()" in msgs[38]

    def test_np_random_in_jit_names_root(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_bad.py": "rng_bad.py"})
        msgs = {v.line: v.message for v in
                detrules.check_rng_discipline(root)}
        assert "np_random_in_jit" in msgs[45]

    def test_suppression_honored(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_bad.py": "rng_bad.py"})
        assert all(v.line != 51
                   for v in detrules.check_rng_discipline(root))

    def test_threaded_idioms_clean(self, tmp_path):
        """Rebinding splits, fan-out, agreed fold_in/seeds, threaded
        numpy seeds, and loop threading are the sanctioned idioms."""
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/models/rng_good.py": "rng_good.py"})
        got = detrules.check_rng_discipline(root)
        assert got == [], [v.render() for v in got]


class TestReductionOrder:
    def test_fixture_violations_caught(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/ops/redorder_bad.py": "redorder_bad.py"})
        got = detrules.check_reduction_order(root)
        assert {v.line for v in got} == {12, 13, 21, 27, 36, 45}, \
            [v.render() for v in got]

    def test_dict_iteration_only_in_pinned_paths(self, tmp_path):
        """Dict iteration order is insertion order — only geometry-shaped
        in the bitwise-pinned dirs; set iteration is flagged anywhere."""
        root = _tmp_tree(
            tmp_path,
            {"kungfu_tpu/utils/redorder_bad.py": "redorder_bad.py"})
        lines = {v.line for v in detrules.check_reduction_order(root)}
        assert 36 not in lines       # dict .items() fold: pinned dirs only
        assert {12, 13, 21, 27, 45} <= lines

    def test_suppression_honored(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/ops/redorder_bad.py": "redorder_bad.py"})
        assert all(v.line != 53
                   for v in detrules.check_reduction_order(root))

    def test_sorted_escape_hatch_clean(self, tmp_path):
        root = _tmp_tree(
            tmp_path,
            {"kungfu_tpu/ops/redorder_good.py": "redorder_good.py"})
        got = detrules.check_reduction_order(root)
        assert got == [], [v.render() for v in got]


class TestTaintEngine:
    """Direct pins on the interprocedural engine under the rules."""

    def test_helper_summary_has_param_flow(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        eng = taint.taint_engine(root)
        tag_for = next(f for f in eng.graph.functions
                       if f.name == "_tag_for")
        summ = eng.summary(tag_for)
        assert 0 in summ.param_flows  # suffix flows into the return

    def test_source_summary_returns_taint(self, tmp_path):
        root = _tmp_tree(tmp_path,
                         {"kungfu_tpu/elastic/taint_bad.py": "taint_bad.py"})
        eng = taint.taint_engine(root)
        stamp = next(f for f in eng.graph.functions if f.name == "_stamp")
        kinds = {t.kind for t in eng.summary(stamp).ret}
        assert kinds == {"time"}

    def test_recursion_terminates(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/mod.py":
                "import time\n\n\n"
                "def a(n):\n"
                "    if n <= 0:\n"
                "        return time.time()\n"
                "    return b(n - 1)\n\n\n"
                "def b(n):\n"
                "    return a(n)\n\n\n"
                "def use(peer, workers, n):\n"
                "    payload = str(a(n)).encode()\n"
                "    peer.channel.consensus_bytes(payload, workers, name='r')\n",
        })
        got = detrules.check_replay_taint(root)
        # the cycle back edge returns the empty summary, but the direct
        # time.time() return in `a` still reaches the sink
        assert [v.line for v in got] == [16], [v.render() for v in got]


class TestDetMutationProof:
    """The acceptance criterion: each seeded mutation on a copy of the
    real tree flips exactly its rule red; the unmutated copies pass all
    three rules with no baseline."""

    _FILES = {
        "kungfu_tpu/elastic/persist.py": ("elastic", "persist.py"),
        "kungfu_tpu/parallel/train.py": ("parallel", "train.py"),
        "kungfu_tpu/ops/schedules.py": ("ops", "schedules.py"),
    }

    def _tree(self, tmp_path, mutate=None):
        files = {}
        for rel, (sub, fn) in self._FILES.items():
            src = open(os.path.join(ROOT, "kungfu_tpu", sub, fn)).read()
            if mutate and fn in mutate:
                mutated = mutate[fn](src)
                assert mutated != src, f"mutation must change {fn}"
                src = mutated
            files[rel] = src
        return _tmp_tree(tmp_path, files)

    def test_unmutated_copies_clean(self, tmp_path):
        root = self._tree(tmp_path)
        got = _det_check_all(root)
        assert got == [], [v.render() for v in got]

    def test_persist_digest_entropy_caught(self, tmp_path):
        """Manifest digest derived from time.time() instead of the
        payload: the ok record can never verify on replay."""
        root = self._tree(tmp_path, mutate={
            "persist.py": lambda s: s.replace(
                "digest = hashlib.blake2b(payload, "
                "digest_size=16).hexdigest()",
                "digest = hashlib.blake2b(str(time.time()).encode(), "
                "digest_size=16).hexdigest()"),
        })
        got = [v for v in detrules.check_replay_taint(root)
               if v.path.endswith("persist.py")]
        assert got, "replay-taint must flag the entropy digest"
        assert any("time.time()" in v.message for v in got), \
            [v.render() for v in got]

    def test_train_key_reuse_caught(self, tmp_path):
        """Dropping the rebinding on the first split leaves `key` dead
        but reconsumed by the next split."""
        root = self._tree(tmp_path, mutate={
            "train.py": lambda s: s.replace(
                "key, k = jax.random.split(key)",
                "k = jax.random.split(key)[1]", 1),
        })
        got = [v for v in detrules.check_rng_discipline(root)
               if v.path.endswith("train.py")]
        assert got, "rng-discipline must flag the key reuse"
        assert any("`key`" in v.message for v in got), \
            [v.render() for v in got]

    def test_schedules_set_iteration_caught(self, tmp_path):
        """Folding scatter slabs over set(widths) unpins the bucket
        order the bitwise-replay contract depends on."""
        root = self._tree(tmp_path, mutate={
            "schedules.py": lambda s: s.replace(
                "for w in widths:", "for w in set(widths):", 1),
        })
        got = [v for v in detrules.check_reduction_order(root)
               if v.path.endswith("schedules.py")]
        assert got, "reduction-order must flag the set fold"
        assert any("set(...)" in v.message for v in got), \
            [v.render() for v in got]

    def test_mutations_fail_the_cli(self, tmp_path, capsys):
        """The same flip through the kflint CLI (what check.sh runs)."""
        root = self._tree(tmp_path, mutate={
            "schedules.py": lambda s: s.replace(
                "for w in widths:", "for w in set(widths):", 1),
        })
        args = ["--root", root]
        for c in DET_CHECKERS:
            args += ["--checker", c]
        assert cli_main(args) == 1
        capsys.readouterr()


class TestChangedCoupled:
    """The --changed cross-language fix: a transport.cpp-only change
    must still surface wire-contract findings (attributed to host.py)."""

    def test_expand_coupled_closes_over_the_pair(self):
        got = expand_coupled(["kungfu_tpu/native/transport.cpp"])
        assert "kungfu_tpu/comm/host.py" in got
        assert "kungfu_tpu/native/transport.cpp" in got
        # unrelated changes stay as-is
        assert expand_coupled(["kungfu_tpu/ops/schedules.py"]) == {
            "kungfu_tpu/ops/schedules.py"}

    def test_cpp_only_change_surfaces_wire_contract(self, tmp_path,
                                                    monkeypatch, capsys):
        host = open(os.path.join(ROOT, "kungfu_tpu", "comm",
                                 "host.py")).read()
        cpp = open(os.path.join(ROOT, "kungfu_tpu", "native",
                                "transport.cpp")).read()
        # a kMagic drift is found by diffing BOTH sides, but the finding
        # is attributed to host.py — exactly the path the old --changed
        # filter dropped when only the .cpp changed
        mutated = cpp.replace("0x4B465450", "0x4B465451")
        assert mutated != cpp
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/comm/host.py": host,
            "kungfu_tpu/native/transport.cpp": mutated,
        })
        from kungfu_tpu.analysis import cli as cli_mod
        monkeypatch.setattr(
            cli_mod, "_git_changed_files",
            lambda root: ["kungfu_tpu/native/transport.cpp"])
        rc = cli_main(["--root", root, "--changed",
                       "--checker", "wire-contract"])
        out = capsys.readouterr()
        assert rc == 1, out.out + out.err
        assert "host.py" in out.out + out.err


class TestCheckWiring:
    """check.sh / Makefile carry the kf-det empty-baseline gate."""

    def test_check_sh_has_det_gate(self):
        text = open(os.path.join(ROOT, "scripts", "check.sh")).read()
        for name in DET_CHECKERS:
            assert f"--checker {name}" in text, name

    def test_makefile_has_detcheck(self):
        text = open(os.path.join(ROOT, "Makefile")).read()
        assert "detcheck" in text
        for name in DET_CHECKERS:
            assert name in text

    def test_full_cli_clean_on_tree(self):
        """The empty-baseline acceptance gate on the real tree."""
        rc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "kflint"),
             "--checker", "replay-taint", "--checker", "rng-discipline",
             "--checker", "reduction-order"],
            capture_output=True, timeout=120,
        )
        assert rc.returncode == 0, \
            rc.stdout.decode() + rc.stderr.decode()


class TestDetSingleParse:
    """The det rules ride the shared parse cache: one parse per file
    even with the engine, the call graph, and the axis env all active."""

    def test_det_rules_share_the_parse_cache(self, tmp_path):
        root = _tmp_tree(tmp_path, {
            "kungfu_tpu/elastic/taint_bad.py": "taint_bad.py",
            "kungfu_tpu/models/rng_bad.py": "rng_bad.py",
            "kungfu_tpu/ops/redorder_bad.py": "redorder_bad.py",
        })
        core.clear_parse_cache()
        _det_check_all(root)
        counts = {p: c for p, c in core.PARSE_COUNTS.items()
                  if p.startswith(str(tmp_path))}
        assert len(counts) == 3, counts
        assert all(c == 1 for c in counts.values()), counts
