"""Seeded randomized sweep of the collective engine.

The strategy/op matrix tests pin fixed shapes; this sweeps random
(size, dtype, op, strategy) tuples — chunk-boundary sizes, narrow int
dtypes, f16 — over a live 3-peer cluster, cross-checked against numpy.
Mirrors the reference's integration sweep
(``scripts/tests/run-integration-tests.sh`` runs np∈1..4 × all 8
strategies over fake buffers); the random sizing is the part fixed
shapes can't cover (a chunk-count bug shows up only at sizes straddling
the chunk size).
"""

import numpy as np
import pytest

from kungfu_tpu.plan import Cluster, PeerList, Strategy

from tests._util import run_all as _run_all

DTYPES = [np.float32, np.float64, np.float16, np.int32, np.int64, np.uint8]
OPS = ["sum", "min", "max", "prod"]
STRATS = [
    Strategy.STAR, Strategy.RING, Strategy.TREE, Strategy.BINARY_TREE,
    Strategy.BINARY_TREE_STAR, Strategy.CLIQUE, Strategy.MULTI_STAR,
    Strategy.MULTI_BINARY_TREE_STAR,
]


@pytest.fixture(params=["native", "python"])
def peers(request, monkeypatch):
    monkeypatch.setenv(
        "KF_NATIVE_ENGINE", "1" if request.param == "native" else "0"
    )
    from kungfu_tpu.peer import Peer
    from kungfu_tpu.utils.envs import Config

    base = 28431 if request.param == "native" else 28441
    workers = PeerList.parse(
        ",".join(f"127.0.0.1:{base + i}" for i in range(3))
    )
    cluster = Cluster(PeerList.parse("127.0.0.1:38098"), workers)
    ps = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
    for p in ps:
        p.start()
    yield ps
    for p in ps:
        p.close()


def _reference(data, op, dt):
    acc = data[0].astype(np.float64)
    for d in data[1:]:
        if op == "sum":
            acc = acc + d
        elif op == "min":
            acc = np.minimum(acc, d)
        elif op == "max":
            acc = np.maximum(acc, d)
        else:
            acc = acc * d
    return acc.astype(dt)


def test_randomized_allreduce_sweep(peers):
    rng = np.random.default_rng(20260730)
    for trial in range(12):
        n = int(rng.integers(1, 200_000))
        dt = DTYPES[int(rng.integers(len(DTYPES)))]
        op = OPS[int(rng.integers(len(OPS)))]
        strat = STRATS[int(rng.integers(len(STRATS)))]
        if np.issubdtype(dt, np.floating):
            data = [rng.standard_normal(n).astype(dt) for _ in range(3)]
            if op == "prod":
                data = [np.abs(d) + 0.5 for d in data]
        else:
            data = [rng.integers(1, 3, n).astype(dt) for _ in range(3)]
        for p in peers:
            p.engine().set_strategy(strat)
        outs = _run_all(
            [
                lambda p=p, d=d: p.engine().all_reduce(
                    d, op=op, name=f"fz{trial}"
                )
                for p, d in zip(peers, data)
            ]
        )
        ref = _reference(data, op, dt)
        for o in outs:
            if dt is np.float16:
                np.testing.assert_allclose(
                    o.astype(np.float64), ref.astype(np.float64),
                    rtol=2e-2, atol=1e-2,
                )
            elif np.issubdtype(dt, np.floating):
                np.testing.assert_allclose(o, ref, rtol=1e-5, atol=1e-5)
            else:
                np.testing.assert_array_equal(o, ref)
