"""Test config: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's fake-cluster trick
(``scripts/tests/run-integration-tests.sh`` runs N processes on localhost):
we test all sharding/collective paths on N virtual CPU devices.

Note: the environment preloads jax (axon sitecustomize), so setting
JAX_PLATFORMS via os.environ is too late — use jax.config instead.
XLA_FLAGS is still read at first backend init, which has not happened yet.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
