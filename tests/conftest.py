"""Test config: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's fake-cluster trick
(``scripts/tests/run-integration-tests.sh`` runs N processes on localhost):
we test all sharding/collective paths on N virtual CPU devices.

Note: the environment preloads jax (axon sitecustomize), so setting
JAX_PLATFORMS via os.environ is too late — use jax.config instead.
XLA_FLAGS is still read at first backend init, which has not happened yet.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(autouse=True)
def _reload_launch_knobs():
    """Launch-set knobs (KF_TPU_XENT, KF_PALLAS_COLLECTIVES, ...) are
    read at import, not at trace time (recompile-hazard hoist): tests
    that monkeypatch them call ``.reload()`` themselves; this teardown
    re-reads the restored environment through the shared registry so a
    mutation can never leak into the next test."""
    yield
    import kungfu_tpu.ops.pallas  # noqa: F401 — registers its knobs
    from kungfu_tpu.utils.envs import reload_launch_knobs

    reload_launch_knobs()
