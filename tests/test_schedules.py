"""Device-plane allreduce schedules (SURVEY §7 step 9: strategy choice =
choice among compiled collective decompositions).

Every schedule must produce the SAME values as ``lax.psum``-family
reference collectives — on the 8-device virtual CPU mesh (conftest), for
ragged sizes that exercise the padding path, and for the int dtypes whose
pad identity differs from float.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from kungfu_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES, all_reduce_scheduled

N_DEV = 8


def _mesh():
    return Mesh(np.asarray(jax.devices()[:N_DEV]), ("x",))


def _run(schedule, op, x):
    """x: [N_DEV, ...] stacked input; returns the allreduced stack."""
    mesh = _mesh()

    def body(s):
        return all_reduce_scheduled(s, "x", op=op, schedule=schedule)

    f = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
    return jax.jit(f)(x)


def _reference(op, x):
    red = {
        "sum": np.sum, "mean": np.mean, "min": np.min, "max": np.max,
    }[op](np.asarray(x, np.float64 if x.dtype != np.int32 else np.int64),
          axis=0)
    return np.broadcast_to(red, x.shape)


class TestSchedules:
    @pytest.mark.parametrize("schedule", ["two_stage", "ring"])
    @pytest.mark.parametrize("op", ["sum", "mean", "min", "max"])
    @pytest.mark.parametrize("length", [1, 7, 64, 1000])
    def test_matches_reference(self, schedule, op, length):
        rng = np.random.RandomState(hash((schedule, op, length)) % 2**31)
        x = jnp.asarray(rng.randn(N_DEV, length), jnp.float32)
        out = _run(schedule, op, x)
        ref = _reference(op, np.asarray(x))
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("schedule", ["two_stage", "ring"])
    @pytest.mark.parametrize("op", ["min", "max"])
    def test_int_dtypes_pad_identity(self, schedule, op):
        """A 0/inf pad would corrupt int min/max on the ragged tail."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randint(-1000, 1000, (N_DEV, 13)), jnp.int32)
        out = _run(schedule, op, x)
        ref = _reference(op, np.asarray(x))
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_psum_schedule_is_default_path(self):
        x = jnp.asarray(np.arange(N_DEV * 4, dtype=np.float32).reshape(N_DEV, 4))
        out = _run("psum", "sum", x)
        np.testing.assert_allclose(np.asarray(out), _reference("sum", np.asarray(x)))

    def test_pytree_input(self):
        rng = np.random.RandomState(0)
        tree = {
            "w": jnp.asarray(rng.randn(N_DEV, 5), jnp.float32),
            "b": jnp.asarray(rng.randn(N_DEV, 3), jnp.float32),
        }
        mesh = _mesh()

        def body(s):
            return all_reduce_scheduled(s, "x", op="sum", schedule="ring")

        f = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
        out = jax.jit(f)(tree)
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(out[k]), _reference("sum", np.asarray(tree[k])),
                rtol=1e-5, atol=1e-5)

    def test_errors(self):
        with pytest.raises(ValueError, match="unknown schedule"):
            all_reduce_scheduled(jnp.ones(4), "x", schedule="tree")
        with pytest.raises(ValueError, match="unsupported op"):
            all_reduce_scheduled(jnp.ones(4), "x", op="prod", schedule="ring")

    def test_tuple_axes_hierarchical(self):
        """(outer, inner) axis tuples: inner folds by psum, the schedule
        runs the outer (cross-host) stage; values match a plain psum."""
        mesh = Mesh(np.asarray(jax.devices()[:N_DEV]).reshape(2, 4),
                    ("h", "l"))
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(N_DEV, 21), jnp.float32)

        def body(s):
            return all_reduce_scheduled(s, ("h", "l"), op="mean",
                                        schedule="ring")

        f = shard_map(body, mesh=mesh, in_specs=(P(("h", "l")),),
                      out_specs=P(("h", "l")))
        out = jax.jit(f)(x)
        np.testing.assert_allclose(np.asarray(out),
                                   _reference("mean", np.asarray(x)),
                                   rtol=1e-5, atol=1e-5)


class TestScheduleFuzz:
    @pytest.mark.slow  # compile-heavy e2e; full tier + CI slow job
    def test_randomized_sweep_matches_psum(self):
        """Seeded randomized sweep (the engine-fuzz analog for the device
        plane): random shapes/dtypes/ops/schedules/mesh splits must all
        agree with the psum reference."""
        rng = np.random.RandomState(20260731)
        for trial in range(25):
            local = int(rng.choice([1, 2, 4, 8]))
            schedule = str(rng.choice(["two_stage", "ring"]))
            op = str(rng.choice(["sum", "mean", "min", "max"]))
            length = int(rng.randint(1, 300))
            dtype = rng.choice([np.float32, np.int32])
            if dtype is np.int32:
                x = rng.randint(-1000, 1000, (N_DEV, length)).astype(np.int32)
                if op == "mean":
                    op = "sum"  # int mean: ill-defined either way
            else:
                x = rng.randn(N_DEV, length).astype(np.float32)
            mesh = Mesh(np.asarray(jax.devices()[:N_DEV]).reshape(
                N_DEV // local, local), ("h", "l"))

            def body(s, op=op, schedule=schedule):
                return all_reduce_scheduled(s, ("h", "l"), op=op,
                                            schedule=schedule)

            f = shard_map(body, mesh=mesh, in_specs=(P(("h", "l")),),
                          out_specs=P(("h", "l")))
            got = np.asarray(jax.jit(f)(jnp.asarray(x)))
            ref = _reference(op, x)
            if x.dtype == np.int32:
                np.testing.assert_array_equal(
                    got, ref.astype(got.dtype),
                    err_msg=f"trial {trial}: {schedule}/{op}/{length}/"
                            f"{local}")
            else:
                np.testing.assert_allclose(
                    got, ref, rtol=1e-5, atol=1e-5,
                    err_msg=f"trial {trial}: {schedule}/{op}/{length}/"
                            f"{local}")


class TestCommunicatorStrategy:
    """Strategy selection on the eager Communicator (the reference's
    ``SetGlobalStrategy`` analog, ``session/adaptation.go:8-28``)."""

    def _comm(self, local_size):
        from kungfu_tpu.comm.device import Communicator

        return Communicator(devices=jax.devices()[:N_DEV],
                            local_size=local_size)

    @pytest.mark.parametrize("local_size", [1, 4, 8])
    @pytest.mark.parametrize("strategy", ALLREDUCE_SCHEDULES)
    def test_all_strategies_match_psum(self, local_size, strategy):
        """Flat (1xN, Nx1) and hierarchical (2x4) meshes; the
        hierarchical case applies the schedule to the cross-host stage."""
        comm = self._comm(local_size)
        comm.set_strategy(strategy)
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(N_DEV, 33), jnp.float32)
        for op in ("sum", "mean", "max"):
            out = comm.all_reduce(x, op=op)
            ref = _reference(op, np.asarray(x))
            np.testing.assert_allclose(np.asarray(out), ref,
                                       rtol=1e-5, atol=1e-5)

    def test_swap_recompiles_and_caches(self):
        comm = self._comm(8)
        x = jnp.ones((N_DEV, 4), jnp.float32)
        comm.all_reduce(x)
        n0 = len(comm._fns)
        comm.set_strategy("ring")
        comm.all_reduce(x)  # new cache entry under the ring key
        assert len(comm._fns) == n0 + 1
        comm.set_strategy("psum")
        comm.all_reduce(x)  # back to the original compiled program
        assert len(comm._fns) == n0 + 1

    @pytest.mark.parametrize("strategy", ["two_stage", "ring"])
    def test_sub_axis_collectives_honor_axes(self, strategy):
        """local_/cross_all_reduce under a non-psum strategy must reduce
        over their OWN axis, not the whole mesh (regression: the
        scheduled body once ignored the requested axes and silently
        computed a global sum)."""
        comm = self._comm(4)  # 2 hosts x 4 local
        comm.set_strategy(strategy)
        x = jnp.asarray(np.arange(N_DEV * 2, dtype=np.float32).reshape(N_DEV, 2))
        xa = np.asarray(x)
        local = np.asarray(comm.local_all_reduce(x, op="mean"))
        # per-host means, replicated within each host's block of 4
        for h in range(2):
            blk = xa[4 * h:4 * h + 4]
            np.testing.assert_allclose(local[4 * h:4 * h + 4],
                                       np.broadcast_to(blk.mean(0), blk.shape),
                                       rtol=1e-6)
        cross = np.asarray(comm.cross_all_reduce(x, op="sum"))
        # peers with the same local rank sum across the 2 hosts
        for l in range(4):
            pair = xa[[l, 4 + l]]
            np.testing.assert_allclose(cross[[l, 4 + l]],
                                       np.broadcast_to(pair.sum(0), pair.shape),
                                       rtol=1e-6)
        # flat mesh: cross is a no-op under every strategy
        flat = self._comm(8)
        flat.set_strategy(strategy)
        np.testing.assert_allclose(np.asarray(flat.cross_all_reduce(x)), xa)

    @pytest.mark.parametrize("strategy", ["two_stage", "ring"])
    def test_bool_min_max(self, strategy):
        """bool consensus-style reduces must not be strategy-dependent
        (regression: _pad_identity crashed on bool via jnp.iinfo)."""
        comm = self._comm(8)
        comm.set_strategy(strategy)
        x = jnp.asarray(np.random.RandomState(0).rand(N_DEV, 5) > 0.5)
        got_max = np.asarray(comm.all_reduce(x, op="max"))
        got_min = np.asarray(comm.all_reduce(x, op="min"))
        xa = np.asarray(x)
        np.testing.assert_array_equal(
            got_max, np.broadcast_to(xa.max(0), xa.shape))
        np.testing.assert_array_equal(
            got_min, np.broadcast_to(xa.min(0), xa.shape))

    def test_env_contract_sets_initial_strategy(self):
        """KF_DEVICE_STRATEGY (the launcher's -device-strategy) seeds the
        peer's schedule — the reference's KUNGFU_ALLREDUCE_STRATEGY
        contract, device plane."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.runner.job import Job
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.utils import envs as E

        peer = Peer(config=E.parse_config_from_env(
            {E.DEVICE_STRATEGY: "two_stage"}))
        assert peer.communicator().strategy == "two_stage"
        # and the launcher writes it into worker envs
        hl = HostList.parse("127.0.0.1:2")
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(2))
        job = Job(prog="python3", args=["t.py"], device_strategy="ring")
        p = job.new_proc(cluster.workers[0], cluster)
        assert p.envs[E.DEVICE_STRATEGY] == "ring"

    def test_strategy_survives_mesh_epoch_rebuild(self):
        """A resize rebuilds the mesh, not the user's strategy decision:
        the next mesh epoch's Communicator inherits the installed
        schedule."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils import envs as E

        peer = Peer(config=E.parse_config_from_env({}))
        comm0 = peer.communicator()
        comm0.set_strategy("ring")
        # what _propose/await_rejoin do on a genuine membership change:
        # retire the communicator object BEFORE the version moves (the
        # naive `_comm = None` here is how the strategy once got lost)
        with peer._lock:
            peer._retire_comm()
        peer.cluster_version += 1
        comm1 = peer.communicator()
        assert comm1 is not comm0
        assert comm1.strategy == "ring"

    def test_strategy_blob_survives_gossip_churn(self):
        """The epoch strategy record lives in the control store, not the
        gossip window: 3+ per-step model saves must not evict it, and a
        re-publish with a longer strategy name must not raise (fixed
        width)."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.store.p2p import remote_request
        from kungfu_tpu.utils import envs as E

        peer = Peer(config=E.parse_config_from_env({}))
        peer._ctrl_store.save(Peer._STRATEGY_BLOB, "psum".ljust(32).encode(),
                              version="0")
        # gossip churn: per-step versions roll the gossip store's window
        for step in range(5):
            peer.save("model", b"x" * 8, version=str(step))
        got = remote_request(peer, peer.config.self_id, Peer._STRATEGY_BLOB,
                             version="0")
        assert got is not None and got.decode().strip() == "psum"
        # re-publish a longer name for the same version: fixed width
        peer._ctrl_store.save(Peer._STRATEGY_BLOB,
                              "two_stage".ljust(32).encode(), version="0")
        got = remote_request(peer, peer.config.self_id, Peer._STRATEGY_BLOB,
                             version="0")
        assert got.decode().strip() == "two_stage"

    def test_set_strategy_racing_a_resize_still_lands(self):
        """set_strategy made on a communicator the resize just retired
        must still reach the next epoch (the on_strategy_change hook
        records it on the Peer durably)."""
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.utils import envs as E

        peer = Peer(config=E.parse_config_from_env({}))
        comm0 = peer.communicator()
        with peer._lock:
            peer._retire_comm()  # a concurrent resize got there first
        comm0.set_strategy("two_stage")  # user's call on the old object
        peer.cluster_version += 1
        assert peer.communicator().strategy == "two_stage"

    def test_unknown_strategy_rejected(self):
        comm = self._comm(8)
        with pytest.raises(ValueError, match="unknown strategy"):
            comm.set_strategy("BINARY_TREE_STAR")

    def test_autotune_picks_and_installs(self):
        """autotune_strategy returns a valid schedule, installs it, and
        results stay correct under the winner (the measured AUTO analog
        of reference strategy.go:90-99)."""
        comm = self._comm(8)
        winner = comm.autotune_strategy(nbytes=1 << 12, trials=1)
        assert winner in ALLREDUCE_SCHEDULES
        assert comm.strategy == winner
        x = jnp.asarray(np.random.RandomState(2).randn(N_DEV, 9), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(comm.all_reduce(x, op="mean")),
            _reference("mean", np.asarray(x)), rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("schedule", ALLREDUCE_SCHEDULES)
    def test_schedule_reaches_the_training_step(self, schedule):
        """synchronous_sgd(schedule=...) compiles the decomposition into
        the hot path: one dp_train_step over a hierarchical mesh must
        produce identical params under every schedule."""
        import optax

        from kungfu_tpu.optimizers import synchronous_sgd
        from kungfu_tpu.parallel.train import dp_train_step

        comm = self._comm(4)  # 2 hosts x 4 local

        def loss_fn(params, batch):
            x, y = batch
            pred = x @ params["w"]
            return jnp.mean((pred - y) ** 2)

        rng = np.random.RandomState(0)
        params0 = {"w": jnp.asarray(rng.randn(3), jnp.float32)}
        batch = (jnp.asarray(rng.randn(16, 3), jnp.float32),
                 jnp.asarray(rng.randn(16), jnp.float32))
        def run(sched):
            tx = synchronous_sgd(optax.sgd(0.1), comm.axis, schedule=sched)
            step = dp_train_step(loss_fn, tx, comm)
            p1, _, loss = step(params0, tx.init(params0), batch)
            assert np.isfinite(float(loss))
            return np.asarray(p1["w"])

        # psum reference computed inline so the pin holds under any test
        # selection/ordering
        np.testing.assert_allclose(run(schedule), run("psum"),
                                   rtol=1e-5, atol=1e-6)

    def test_device_strategy_driver(self):
        """Step-time regression → collective re-autotune → caller told to
        re-jit; healthy windows track the baseline instead."""
        from kungfu_tpu.monitor import DeviceStrategyDriver

        comm = self._comm(8)
        drv = DeviceStrategyDriver(comm, check_every=4, regression=1.5,
                                   consecutive=2, autotune_nbytes=1 << 10)
        # healthy baseline windows
        for _ in range(8):
            assert not drv.observe(0.010)
        # a single bad window must NOT trigger (consecutive=2)
        for _ in range(4):
            assert not drv.observe(0.030)
        # second consecutive bad window triggers the re-tune
        fired = [drv.observe(0.030) for _ in range(4)]
        assert fired[:3] == [False, False, False] and fired[3]
        assert drv.swaps == 1
        assert comm.strategy in ALLREDUCE_SCHEDULES
        # the new schedule re-establishes its own baseline: the next
        # window only seeds, no instant re-trigger
        for _ in range(4):
            assert not drv.observe(0.030)
        for _ in range(4):
            assert not drv.observe(0.030)
        assert drv.swaps == 1

    def test_ctor_strategy(self):
        from kungfu_tpu.comm.device import Communicator

        comm = Communicator(devices=jax.devices()[:N_DEV], local_size=8,
                            strategy="two_stage")
        assert comm.strategy == "two_stage"
        x = jnp.ones((N_DEV, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(comm.all_reduce(x)),
                                   np.full((N_DEV, 4), 8.0))


class TestBucketedScatterGather:
    """reduce_scatter_flat / all_gather_flat: the ZeRO collective pair.
    Bucketing is pure program structure — results must be bit-identical
    across bucket layouts, and the pair must round-trip the mesh-major
    chunk geometry exactly."""

    def _mesh(self, n=8):
        return Mesh(np.array(jax.devices()[:n]), ("d",))

    def test_reduce_scatter_matches_psum_slice(self):
        from kungfu_tpu.ops.schedules import reduce_scatter_flat

        n, chunk = 8, 5
        mesh = self._mesh(n)
        rng = np.random.RandomState(0)
        x = rng.randn(n, n * chunk).astype(np.float32)  # per-device rows

        def body(row):
            return reduce_scatter_flat(row[0], ["d"], chunk)

        out = shard_map(body, mesh=mesh, in_specs=P("d"),
                        out_specs=P("d"))(x)
        want = x.sum(0)  # the reduced flat buffer
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    @pytest.mark.parametrize("widths", [None, [1] * 5, [2, 3], [4, 1]])
    def test_bucketing_is_bitwise_invariant(self, widths):
        from kungfu_tpu.ops.schedules import reduce_scatter_flat

        n, chunk = 8, 5
        mesh = self._mesh(n)
        rng = np.random.RandomState(1)
        x = rng.randn(n, n * chunk).astype(np.float32)

        def run(w):
            body = lambda row: reduce_scatter_flat(row[0], ["d"], chunk, w)
            return np.asarray(shard_map(
                body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))(x))

        np.testing.assert_array_equal(run(widths), run(None))

    def test_gather_inverts_scatter(self):
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              reduce_scatter_flat)

        n, chunk = 8, 3
        mesh = self._mesh(n)
        rng = np.random.RandomState(2)
        x = rng.randn(n, n * chunk).astype(np.float32)

        def body(row):
            shard = reduce_scatter_flat(row[0], ["d"], chunk, [2, 1])
            return all_gather_flat(shard, ["d"], [2, 1])[None]

        out = np.asarray(shard_map(body, mesh=mesh, in_specs=P("d"),
                                   out_specs=P("d"))(x))
        want = x.sum(0)
        for r in range(n):  # every device sees the full reduced buffer
            np.testing.assert_allclose(out[r], want, rtol=1e-5)

    def test_empty_axes_is_identity(self):
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              reduce_scatter_flat)

        x = jnp.arange(6, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(reduce_scatter_flat(x, [], 6)), np.asarray(x))
        np.testing.assert_array_equal(
            np.asarray(all_gather_flat(x, [])), np.asarray(x))

    # kf-overlap satellite: serial vs pipelined bucket sequencing is a
    # SCHEDULING property only — results pinned bitwise for all bucket
    # counts, including the 1-bucket and padded-tail degenerate cases
    # (chunk=5, widths [4,1]/[2,3] leave a tail narrower than the body;
    # chunk 5 over n=8 means the last devices' rows are pure padding in
    # the zero geometry — the shapes below exercise both).
    @pytest.mark.parametrize("widths", [None, [5], [2, 3], [4, 1], [1] * 5])
    def test_serial_pipelined_bitwise(self, widths):
        from kungfu_tpu.ops.schedules import reduce_scatter_flat

        n, chunk = 8, 5
        mesh = self._mesh(n)
        rng = np.random.RandomState(3)
        x = rng.randn(n, n * chunk).astype(np.float32)

        def run(serial):
            body = lambda row: reduce_scatter_flat(
                row[0], ["d"], chunk, widths, serial=serial)
            return np.asarray(shard_map(
                body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))(x))

        a, b = run(False), run(True)
        assert a.tobytes() == b.tobytes()

    @pytest.mark.parametrize("widths", [None, [3], [2, 1], [1] * 3])
    def test_all_gather_prefetch_bitwise(self, widths):
        from kungfu_tpu.ops.schedules import all_gather_flat

        n, chunk = 8, 3
        mesh = self._mesh(n)
        rng = np.random.RandomState(4)
        shards = rng.randn(n * chunk).astype(np.float32)

        def run(prefetch):
            body = lambda s: all_gather_flat(
                s, ["d"], widths, prefetch=prefetch)[None]
            return np.asarray(shard_map(
                body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))(shards))

        a, b = run(False), run(True)
        assert a.tobytes() == b.tobytes()

    def test_prefetch_gradient_path_bitwise(self):
        """The ZeRO-3 shape: grad through the prefetch fence (its custom
        vjp barriers the cotangents) must be bitwise the plain path's
        gradient — the fence is a value identity in both directions."""
        from kungfu_tpu.ops.schedules import all_gather_flat

        n, chunk = 4, 6
        mesh = self._mesh(n)
        rng = np.random.RandomState(5)
        shards = rng.randn(n * chunk).astype(np.float32)
        w = rng.randn(n * chunk).astype(np.float32)

        def grad_of(prefetch):
            def loss_body(s):
                full = all_gather_flat(s, ["d"], [2, 2, 2],
                                       prefetch=prefetch)
                return jnp.sum(full * w) * jnp.ones((1,))

            f = shard_map(loss_body, mesh=mesh, in_specs=P("d"),
                          out_specs=P(None))
            return np.asarray(jax.grad(
                lambda s: f(s)[0])(jnp.asarray(shards)))

        a, b = grad_of(False), grad_of(True)
        assert a.tobytes() == b.tobytes()

    def test_gather_transpose_is_reduce_scatter(self):
        """grad(loss(all_gather_flat(shard))) must arrive already
        reduce-scattered — the ZeRO-3 gradient path costs no extra
        collective.  Witnessed structurally: the traced backward program
        contains a reduce_scatter, not a psum + slice."""
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              traced_collective_bytes)

        n, chunk = 8, 4
        mesh = self._mesh(n)

        def body(shard):
            def loss(s):
                return jnp.sum(all_gather_flat(s, ["d"]) ** 2)

            return jax.grad(loss)(shard)

        fn = shard_map(body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        x = jnp.ones((n * chunk,), jnp.float32)
        got = traced_collective_bytes(fn, x, axis_sizes={"d": n})
        assert "reduce_scatter" in got, got


class TestBucketWidths:
    def test_partitions_chunk(self):
        from kungfu_tpu.ops.schedules import bucket_widths

        for chunk, n, item, bb in [(100, 8, 4, 64), (5, 2, 4, 1 << 20),
                                   (7, 3, 2, 12), (1, 8, 4, 1)]:
            w = bucket_widths(chunk, n, item, bb)
            assert sum(w) == chunk and all(x > 0 for x in w)
            per = max(1, bb // (n * item))
            assert all(x <= per for x in w)

    def test_degenerate(self):
        from kungfu_tpu.ops.schedules import bucket_widths

        assert bucket_widths(0, 8, 4, 64) == []
        assert bucket_widths(10, 1, 4, 1 << 30) == [10]


class TestTracedCollectiveBytes:
    """The bench measurement primitive: wire bytes read from the traced
    program, ring convention."""

    def test_psum_cost_exact(self):
        from kungfu_tpu.ops.schedules import traced_collective_bytes

        n = 8
        mesh = Mesh(np.array(jax.devices()[:n]), ("d",))
        m = 16

        def body(row):
            return jax.lax.psum(row[0], "d")[None]

        fn = shard_map(body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        x = jnp.ones((n, m), jnp.float32)
        got = traced_collective_bytes(fn, x, axis_sizes={"d": n})
        want = 2.0 * (n - 1) / n * m * 4
        assert got == {"psum": want}, (got, want)

    def test_single_axis_world_costs_nothing(self):
        from kungfu_tpu.ops.schedules import traced_collective_bytes

        mesh = Mesh(np.array(jax.devices()[:1]), ("d",))

        def body(row):
            return jax.lax.psum(row[0], "d")[None]

        fn = shard_map(body, mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        got = traced_collective_bytes(
            fn, jnp.ones((1, 4), jnp.float32), axis_sizes={"d": 1})
        assert got == {}

    def test_non_collective_program_is_empty(self):
        from kungfu_tpu.ops.schedules import traced_collective_bytes

        got = traced_collective_bytes(
            lambda x: x * 2 + 1, jnp.ones((8,)), axis_sizes={"d": 8})
        assert got == {}
