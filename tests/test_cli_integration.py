"""End-to-end CLI integration: kfrun spawning real worker processes.

Parity with the reference's public-API smoke test
(``kungfu-run -np 4 ./bin/kungfu-test-public-apis``, ci.yaml:41) and the
MNIST SLP convergence test.  Marked slow: each worker pays jax import cost
(single CPU core in CI).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, timeout=240):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return subprocess.run(
        [sys.executable, "-m", "kungfu_tpu.runner.cli"] + args,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )


@pytest.mark.slow
class TestCLI:
    def test_mnist_slp_np2(self):
        r = run_cli(
            ["-np", "2", "-timeout", "200", sys.executable,
             "examples/mnist_slp.py", "--n-epochs", "1"]
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK" in r.stdout

    def test_worker_failure_fails_job(self):
        r = run_cli(
            ["-np", "2", "-timeout", "60", sys.executable, "-c", "import sys; sys.exit(3)"]
        )
        assert r.returncode == 1


class TestCLIParsing:
    def test_parser_flags(self):
        from kungfu_tpu.runner.cli import build_cluster, build_parser

        ns = build_parser().parse_args(
            ["-np", "4", "-H", "127.0.0.1:4", "-strategy", "RING", "prog", "a", "b"]
        )
        assert ns.np == 4 and ns.prog == "prog" and ns.args == ["a", "b"]
        cluster = build_cluster(ns)
        assert cluster.size() == 4

    def test_default_host(self):
        from kungfu_tpu.runner.cli import build_cluster, build_parser

        ns = build_parser().parse_args(["-np", "2", "x"])
        assert build_cluster(ns).size() == 2


class TestTpuBackendEnvContract:
    def test_coordinator_envs_set(self):
        """TPU-backend workers get the jax.distributed world contract."""
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.runner.job import COORDINATOR_PORT_OFFSET, Job
        from kungfu_tpu.utils import envs as E

        hl = HostList.parse("10.0.0.1:2,10.0.0.2:2")
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(4))
        job = Job(prog="python3", args=["t.py"], backend="tpu")
        procs = [job.new_proc(w, cluster) for w in cluster.workers]
        assert len(procs) == 4
        for i, p in enumerate(procs):
            assert p.envs[E.COORDINATOR] == f"10.0.0.1:{cluster.workers[0].port + COORDINATOR_PORT_OFFSET}"
            assert p.envs[E.NUM_PROCESSES] == "4"
            assert p.envs[E.PROCESS_ID] == str(i)
            assert "JAX_PLATFORMS" not in p.envs

    def test_single_worker_no_distributed(self):
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.runner.job import Job
        from kungfu_tpu.utils import envs as E

        hl = HostList.parse("10.0.0.1:1")
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(1))
        job = Job(prog="python3", args=["t.py"], backend="tpu")
        p = job.new_proc(cluster.workers[0], cluster)
        assert E.COORDINATOR not in p.envs
