"""End-to-end CLI integration: kfrun spawning real worker processes.

Parity with the reference's public-API smoke test
(``kungfu-run -np 4 ./bin/kungfu-test-public-apis``, ci.yaml:41) and the
MNIST SLP convergence test.  Marked slow: each worker pays jax import cost
(single CPU core in CI).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, timeout=240):
    return run_cli_prog([sys.executable, "-m", "kungfu_tpu.runner.cli"] + args,
                        timeout=timeout)


def run_cli_prog(cmd, timeout=240):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return subprocess.run(
        cmd, cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env=env,
    )


@pytest.mark.slow
class TestCLI:
    def test_mnist_slp_np2(self):
        r = run_cli(
            ["-np", "2", "-timeout", "200", sys.executable,
             "examples/mnist_slp.py", "--n-epochs", "1"]
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK" in r.stdout

    def test_worker_failure_fails_job(self):
        r = run_cli(
            ["-np", "2", "-timeout", "60", sys.executable, "-c", "import sys; sys.exit(3)"]
        )
        assert r.returncode == 1

    def test_gns_driven_grow_e2e(self):
        """Round-3 VERDICT item 7: rising gradient noise scale triggers a
        grow through monitor → policy → propose → config server → resize,
        in one watch-mode run.  (The GNS ramp is injected via the chaos
        knob; the acted-on pipeline and the per-step REAL estimator both
        run.)"""
        import re

        r = run_cli(
            ["-w", "-builtin-config-port", "9332", "-np", "1",
             "-H", "127.0.0.1:2", "-timeout", "200", sys.executable,
             "examples/gns_elastic.py", "--", "--steps", "10",
             "--synthetic-gns", "24,24,24,96,96,96,96,96,96,96"]
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "GNS-resized 1->2" in r.stdout
        done = re.findall(r"worker (\d+): done size=(\d+)", r.stdout)
        assert len(done) == 2 and all(s == "2" for _, s in done), r.stdout
        # the real estimator produced finite values on the 2-worker phase
        import math

        reals = [float(m) for m in re.findall(r"real_gns=([-\d.einf]+)", r.stdout)]
        assert reals and all(math.isfinite(v) for v in reals), reals

    def test_cifar_elastic_e2e(self):
        """Loader + ElasticDataset + elastic resize in one watch-mode job
        (round-3 VERDICT item 6): grow 1→2 mid-stream, both workers must
        finish on the SAME global sample offset."""
        import re

        r = run_cli(
            ["-w", "-builtin-config-port", "9331", "-np", "1",
             "-H", "127.0.0.1:2", "-timeout", "200", sys.executable,
             "examples/cifar_elastic.py", "--", "--schedule", "1:4,2:4"]
        )
        assert r.returncode == 0, r.stdout + r.stderr
        done = re.findall(
            r"worker (\d+): done step=(\d+) resizes=(\d+) consumed=(\d+).*OK",
            r.stdout,
        )
        assert len(done) == 2, r.stdout
        consumed = {int(c) for _, _, _, c in done}
        assert len(consumed) == 1  # the stream stayed aligned across the resize
        assert any(int(rs) == 1 for _, _, rs, _ in done)  # survivor resized once


@pytest.mark.slow
class TestGossipExample:
    def test_two_workers_mix_and_converge(self, tmp_path):
        """PairAveraging under the REAL launcher: each worker sees only
        its own data slice, so converging to the shared truth proves the
        cross-process model pulls actually mixed the replicas."""
        import glob
        import re

        logdir = str(tmp_path / "logs")
        r = run_cli_prog(
            [sys.executable, "-m", "kungfu_tpu.runner.cli",
             "-np", "2", "-H", "127.0.0.1:2", "-logdir", logdir,
             sys.executable, "examples/gossip_train.py",
             "--", "--steps", "40"],
            timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        rows = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            for ln in open(f):
                m = re.match(r"KFGOSSIP rank=(\d+) size=2 "
                             r"final_loss=([\d.]+) w_err=([\d.]+) "
                             r"pulls=(\d+)", ln)
                if m:
                    rows.append(tuple(float(x) for x in m.groups()))
        assert len(rows) == 2, rows
        for rank, loss, err, pulls in rows:
            assert loss < 0.05 and err < 0.5, rows
            assert pulls == 40


@pytest.mark.slow
class TestHostEngineSystemBench:
    def test_np2_through_launcher(self, tmp_path):
        """Round-3 VERDICT item 6: the system bench must run as REAL
        worker processes under the launcher with the gradient exchange
        through the native host engine (reference
        benchmarks/system/README.md:9-16)."""
        import glob
        import json

        logdir = str(tmp_path / "logs")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "-m", "kungfu_tpu.runner.cli", "-q",
             "-np", "2", "-H", "127.0.0.1:2", "-logdir", logdir,
             sys.executable, "benchmarks/system.py",
             "--", "--backend", "host", "--model", "resnet50", "--quick"],
            cwd=REPO, capture_output=True, text=True, timeout=240, env=env,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        rows = []
        for f in glob.glob(os.path.join(logdir, "*.stdout.log")):
            for ln in open(f):
                if ln.startswith("{"):
                    rows.append(json.loads(ln))
        assert len(rows) == 1  # rank 0 only
        row = rows[0]
        assert row["metric"] == "resnet50_host_engine_steps_per_sec"
        assert row["np"] == 2 and row["value"] > 0
        assert row["model_mib"] > 90


@pytest.mark.slow
class TestLongContextExample:
    def test_ring_sp4_trains(self):
        """SP demo: exactness check vs dense + loss decreases, flash
        blocks forced so the Pallas path runs (interpret mode here)."""
        r = run_cli_prog(
            [sys.executable, "examples/long_context.py", "--sp", "4",
             "--seq-len", "128", "--cpu-devices", "4", "--steps", "3",
             "--d-model", "64", "--block-impl", "flash"],
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "OK" in r.stdout


@pytest.mark.slow
class TestStrategyTourExample:
    def test_tour_runs_all_stages(self):
        """autotune → scheduled training → adaptive re-tune → zero1,
        in one run on the virtual mesh."""
        r = run_cli_prog(
            [sys.executable, "examples/strategy_tour.py",
             "--cpu-devices", "8", "--steps", "18"],
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "[1] autotune" in r.stdout
        # deterministic: the injected-slowdown windows produce exactly one
        # re-tune at --steps 18 (check_every=3, consecutive=2)
        assert "adaptive re-tunes: 1" in r.stdout
        assert "[4] zero1" in r.stdout and "(1/8)" in r.stdout


class TestSelfDiscovery:
    """-self auto (reference runner/discovery.go): probe which -H entry
    this machine holds (bind probe per candidate)."""

    def test_loopback_infers(self):
        from kungfu_tpu.runner.discovery import infer_self_ip

        assert infer_self_ip(["127.0.0.1", "203.0.113.7"]) == "127.0.0.1"

    @pytest.mark.skipif(sys.platform != "linux",
                        reason="whole-127/8 loopback binding is Linux-only")
    def test_ambiguous_aliases_raise(self):
        from kungfu_tpu.runner.discovery import infer_self_ip

        with pytest.raises(RuntimeError, match="pass -self"):
            infer_self_ip(["127.0.0.1", "127.0.0.2"])

    def test_no_local_entry_raises(self):
        from kungfu_tpu.runner.discovery import infer_self_ip

        with pytest.raises(RuntimeError, match="none of"):
            infer_self_ip(["203.0.113.7", "203.0.113.8"])

    def test_cli_wires_auto(self):
        """main() resolves -self auto before building the cluster; with a
        hostless command line it refuses."""
        from kungfu_tpu.runner import cli

        with pytest.raises(SystemExit, match="-self auto needs"):
            # -platform none: the ambient TPU-pod env contract would
            # otherwise fill -H/-self before the check
            cli.main(["-self", "auto", "-platform", "none", "true"])


class TestCLIParsing:
    def test_parser_flags(self):
        from kungfu_tpu.runner.cli import build_cluster, build_parser

        ns = build_parser().parse_args(
            ["-np", "4", "-H", "127.0.0.1:4", "-strategy", "RING", "prog", "a", "b"]
        )
        assert ns.np == 4 and ns.prog == "prog" and ns.args == ["a", "b"]
        cluster = build_cluster(ns)
        assert cluster.size() == 4

    def test_default_host(self):
        from kungfu_tpu.runner.cli import build_cluster, build_parser

        ns = build_parser().parse_args(["-np", "2", "x"])
        assert build_cluster(ns).size() == 2


class TestTpuBackendEnvContract:
    def test_coordinator_envs_set(self):
        """TPU-backend workers get the jax.distributed world contract."""
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.runner.job import COORDINATOR_PORT_OFFSET, Job
        from kungfu_tpu.utils import envs as E

        hl = HostList.parse("10.0.0.1:2,10.0.0.2:2")
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(4))
        job = Job(prog="python3", args=["t.py"], backend="tpu")
        procs = [job.new_proc(w, cluster) for w in cluster.workers]
        assert len(procs) == 4
        for i, p in enumerate(procs):
            assert p.envs[E.COORDINATOR] == f"10.0.0.1:{cluster.workers[0].port + COORDINATOR_PORT_OFFSET}"
            assert p.envs[E.NUM_PROCESSES] == "4"
            assert p.envs[E.PROCESS_ID] == str(i)
            assert "JAX_PLATFORMS" not in p.envs

    def test_single_worker_no_distributed(self):
        from kungfu_tpu.plan import Cluster, HostList
        from kungfu_tpu.runner.job import Job
        from kungfu_tpu.utils import envs as E

        hl = HostList.parse("10.0.0.1:1")
        cluster = Cluster(hl.gen_runner_list(), hl.gen_peer_list(1))
        job = Job(prog="python3", args=["t.py"], backend="tpu")
        p = job.new_proc(cluster.workers[0], cluster)
        assert E.COORDINATOR not in p.envs


@pytest.mark.slow
class TestZeroShrinkE2E:
    """examples/zero_shrink.py: host-plane ZeRO-2 training through a
    LIVE 4->2 shrink (two staged deaths), final params checked BITWISE
    against the non-elastic fixed-world replay from the same state.

    The per-rank gradients in the example are identical by construction
    and every constant is an exact binary fraction, so the elastic run,
    a non-elastic 2-rank run from the same snapshot, and this plain
    numpy replay are all the same float32 sequence — any re-carve error
    (a shifted segment, momentum restored as zeros, a lost buddy chunk)
    breaks equality exactly."""

    def _numpy_reference(self, n_steps=8, total=32):
        import numpy as np

        p = (np.arange(total, dtype=np.float32) / total)
        m = np.zeros(total, np.float32)
        for step in range(n_steps):
            g = (p - np.full(total, step * 0.125, np.float32)).astype(
                np.float32)
            m = (0.5 * m + g).astype(np.float32)
            p = (p - 0.125 * m).astype(np.float32)
        return p

    def test_live_4to2_shrink_bitwise(self):
        import json

        import numpy as np

        r = run_cli(
            ["-np", "4", "-tolerate-failures", "-timeout", "200",
             "-chaos", "die:step=3,rank=3;die:step=5,rank=1",
             sys.executable, "examples/zero_shrink.py", "--n-steps", "8"]
        )
        out = r.stdout + r.stderr
        assert "shrunk to 3 workers; momentum re-carved" in out, out
        assert "shrunk to 2 workers; momentum re-carved" in out, out
        assert "zero2 survived to step 8 on 2 workers" in out, out
        final = [ln for ln in out.splitlines() if "FINAL " in ln]
        assert final, out
        got = np.asarray(
            json.loads(final[0].split("FINAL ", 1)[1]), np.float32)
        np.testing.assert_array_equal(got, self._numpy_reference())


@pytest.mark.slow
class TestMultisliceShrinkE2E:
    """examples/multislice_shrink.py: an emulated 2-slice pod (kfrun
    -num-slices 2, 4 workers slice-major) loses ALL of slice 1 to chaos
    ``die_slice`` at one step boundary and survives IN FLIGHT — the
    slice ladder (whole-slice ping widening, quorum counted in slices,
    exclusion consensus over surviving slice leaders, DCN mesh re-carve,
    momentum re-carved from the cross-slice buddy mirrors) runs instead
    of a detector relaunch.  Final params are checked BITWISE against a
    fixed-world numpy replay from the same committed step: the example's
    gradients are rank-identical and every constant is an exact binary
    fraction, so ANY re-carve error (shifted segment, momentum restored
    as zeros, a same-slice mirror that died with its owner) breaks
    equality exactly.  `make multislice-demo` runs the same scenario."""

    def _numpy_reference(self, n_steps=8, total=32):
        import numpy as np

        p = (np.arange(total, dtype=np.float32) / total)
        m = np.zeros(total, np.float32)
        for step in range(n_steps):
            g = (p - np.full(total, step * 0.125, np.float32)).astype(
                np.float32)
            m = (0.5 * m + g).astype(np.float32)
            p = (p - 0.125 * m).astype(np.float32)
        return p

    def test_slice_kill_survives_bitwise(self):
        import json

        import numpy as np

        r = run_cli(
            ["-np", "4", "-num-slices", "2", "-tolerate-failures",
             "-timeout", "200",
             "-chaos", "die_slice:slice=1,step=3",
             sys.executable, "examples/multislice_shrink.py",
             "--n-steps", "8"]
        )
        out = r.stdout + r.stderr
        # the shrink was slice-granular: 4->2 in ONE hop (both ranks of
        # slice 1 excluded together), not two rank-wise 4->3->2 hops
        assert "slice-shrunk to 2 workers (1 slice(s))" in out, out
        assert "shrunk to 3 workers" not in out, out
        assert "multislice survived to step 8 on 2 workers" in out, out
        final = [ln for ln in out.splitlines() if "FINAL " in ln]
        assert final, out
        got = np.asarray(
            json.loads(final[0].split("FINAL ", 1)[1]), np.float32)
        np.testing.assert_array_equal(got, self._numpy_reference())
