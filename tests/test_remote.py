"""kf-distribute / kf-rrun tests via a local fake-ssh shim.

The shim drops the host argument and executes the command locally —
multi-host launch semantics tested without machines (the reference tests
its SSH path the same way its cluster tests fake multi-node: everything
on localhost, SURVEY §4).
"""

import os
import stat
import sys

import pytest

from kungfu_tpu.runner.remote import main_distribute, main_rrun, ssh_proc


@pytest.fixture
def fake_ssh(tmp_path):
    shim = tmp_path / "fake-ssh"
    shim.write_text("#!/bin/sh\n# $1 = [user@]host, $2 = command string\nshift\nexec sh -c \"$1\"\n")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    return str(shim)


class TestSshProc:
    def test_command_quoting(self):
        p = ssh_proc("10.0.0.1", ["echo", "a b", "$HOME"], user="me")
        assert p.prog == "ssh"
        assert p.args[0] == "me@10.0.0.1"
        assert p.args[1] == "echo 'a b' '$HOME'"

    def test_no_user(self):
        p = ssh_proc("10.0.0.1", ["true"])
        assert p.args[0] == "10.0.0.1"


class TestDistribute:
    def test_runs_on_every_host(self, fake_ssh, tmp_path):
        out = tmp_path / "out"
        rc = main_distribute([
            "-H", "127.0.0.1:2,127.0.0.2:2",
            "--ssh", fake_ssh,
            "-q",
            "sh", "-c", f"echo ran >> {out}",
        ])
        assert rc == 0
        assert open(out).read().splitlines() == ["ran", "ran"]

    def test_failure_propagates(self, fake_ssh):
        rc = main_distribute([
            "-H", "127.0.0.1:1",
            "--ssh", fake_ssh,
            "-q",
            "false",
        ])
        assert rc == 1

    def test_per_host_logs(self, fake_ssh, tmp_path):
        logdir = tmp_path / "logs"
        rc = main_distribute([
            "-H", "127.0.0.1:1",
            "--ssh", fake_ssh,
            "-q",
            "-logdir", str(logdir),
            "echo", "hello-log",
        ])
        assert rc == 0
        assert "hello-log" in open(logdir / "127.0.0.1.stdout.log").read()


@pytest.mark.slow
class TestRrun:
    def test_launches_runner_per_host(self, fake_ssh, tmp_path):
        """Full path: rrun → fake ssh → kfrun → worker procs.

        One host with 2 slots on localhost; the worker just reports its
        env contract."""
        marker = tmp_path / "worker.out"
        script = tmp_path / "worker.py"
        script.write_text(
            "import os\n"
            f"open({str(marker)!r}, 'a').write(os.environ['KF_SELF_SPEC'] + chr(10))\n"
        )
        rc = main_rrun([
            "-np", "2",
            "-H", "127.0.0.1:2",
            "--ssh", fake_ssh,
            "--python", sys.executable,
            "-timeout", "120",
            str(sys.executable), str(script),
        ])
        assert rc == 0
        lines = open(marker).read().splitlines()
        assert len(lines) == 2 and len(set(lines)) == 2  # two distinct workers

    def test_np_over_capacity(self, fake_ssh):
        rc = main_rrun([
            "-np", "4",
            "-H", "127.0.0.1:1",
            "--ssh", fake_ssh,
            "true",
        ])
        assert rc == 1
