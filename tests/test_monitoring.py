"""Monitoring/adaptation tests: counters, /metrics, latencies, MST,
set_tree, interference (reference test_tensorflow_throughput_monitoring.py
/ test_set_tree.py analogs)."""

import time
import urllib.request

import numpy as np
import pytest

from kungfu_tpu.monitor.metrics import MetricsServer, NetMonitor
from kungfu_tpu.plan.mst import minimum_spanning_tree

from tests._util import run_all


class TestNetMonitor:
    def test_counters_and_rates(self):
        m = NetMonitor(period=0.1).start()
        try:
            for _ in range(10):
                m.egress("a:1", 1000)
                m.ingress("b:2", 500)
            time.sleep(0.3)
            totals = m.totals()
            assert totals["egress"]["a:1"] == 10000
            assert totals["ingress"]["b:2"] == 5000
            assert m.egress_rates(["a:1"])[0] >= 0
            assert m.egress_rates(["missing:9"]) == [0.0]
        finally:
            m.stop()

    def test_metrics_endpoint(self):
        m = NetMonitor(period=0.1).start()
        s = MetricsServer(m, port=28123).start()
        try:
            m.egress("peer:1", 2048)
            with urllib.request.urlopen("http://127.0.0.1:28123/metrics", timeout=5) as r:
                text = r.read().decode()
            assert 'kf_egress_bytes_total{peer="peer:1"} 2048' in text
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen("http://127.0.0.1:28123/nope", timeout=5)
        finally:
            s.stop()
            m.stop()


class TestHostNoiseScale:
    """ops/monitor.py::host_noise_scale — the host-plane (engine) GNS
    estimator: the n==1 no-signal contract, and agreement with the
    in-graph ``global_noise_scale`` on identical inputs."""

    def _engines(self, base_port, n):
        from kungfu_tpu.comm.engine import CollectiveEngine
        from kungfu_tpu.comm.host import HostChannel
        from kungfu_tpu.plan import PeerID, PeerList
        from kungfu_tpu.plan.strategy import Strategy

        peers = PeerList.of(*(PeerID("127.0.0.1", base_port + i)
                              for i in range(n)))
        chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
        engines = [CollectiveEngine(c, peers, strategy=Strategy.STAR)
                   for c in chans]
        return chans, engines

    def test_single_worker_reports_no_signal(self):
        """b_small == b_big on one worker: the two-batch estimator is
        undefined; callers must get ``None`` ("no estimate"), not 0.0 —
        a zero would read as a measured noise scale of zero and the
        pulse plane would EMA it into the published gauge."""
        from kungfu_tpu.ops.monitor import host_noise_scale

        chans, engines = self._engines(23720, 1)
        try:
            g = np.random.RandomState(0).uniform(-1, 1, 32).astype(np.float32)
            assert host_noise_scale(engines[0], g, g, 16) is None
        finally:
            for c in chans:
                c.close()

    @pytest.mark.parametrize("n", [3, 5])
    def test_non_power_of_two_world_matches_in_graph(self, n):
        """The one-estimator property across ODD world sizes: the
        host-plane value over a real n-peer engine equals the in-graph
        ``global_noise_scale`` over an n-device mesh on the SAME
        per-peer gradients.  Non-power-of-two sizes exercise the
        b_big = n*b_small arithmetic where a pairwise-halving mental
        model would silently diverge."""
        import jax
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        import kungfu_tpu.ops.collective as kc
        from kungfu_tpu.ops.monitor import global_noise_scale, host_noise_scale
        from kungfu_tpu.utils.jaxcompat import shard_map

        b_small = 8.0
        rng = np.random.RandomState(100 + n)
        base = rng.uniform(1.0, 2.0, 48)
        grads = np.stack(
            [base + 0.1 * rng.uniform(-1, 1, 48) for _ in range(n)]
        ).astype(np.float32)

        chans, engines = self._engines(23740 + 10 * n, n)
        try:
            def one(i):
                avg = engines[i].all_reduce(grads[i], op="mean")
                return host_noise_scale(engines[i], grads[i], avg, b_small)

            host_vals = run_all([lambda i=i: one(i) for i in range(n)])
        finally:
            for c in chans:
                c.close()
        assert all(v is not None for v in host_vals)
        # symmetric: every rank publishes the same estimate
        for v in host_vals[1:]:
            assert host_vals[0] == pytest.approx(v, rel=1e-9)

        mesh = Mesh(np.array(jax.devices()[:n]), ("kf",))

        def gns_fn(g):
            avg = kc.all_reduce(g, "kf", op="mean")
            return global_noise_scale(g, avg, b_small, "kf")[None]

        got = shard_map(gns_fn, mesh=mesh, in_specs=P("kf"),
                        out_specs=P("kf"))(grads)
        in_graph = float(np.asarray(got)[0])
        assert host_vals[0] == pytest.approx(in_graph, rel=1e-3)

    def test_two_peer_engine_matches_in_graph_estimator(self):
        """The host-plane estimate over a real 2-peer CollectiveEngine
        equals the in-graph ``global_noise_scale`` over a 2-device mesh
        on the SAME per-peer gradients — the two planes implement one
        estimator, not two approximations of it."""
        import jax
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        import kungfu_tpu.ops.collective as kc
        from kungfu_tpu.ops.monitor import global_noise_scale, host_noise_scale
        from kungfu_tpu.utils.jaxcompat import shard_map

        b_small = 16.0
        rng = np.random.RandomState(7)
        # base + per-peer noise keeps |G|^2 well away from zero, so the
        # estimator is well-conditioned and float32-vs-float64 plane
        # differences stay in the mantissa, not the structure
        base = rng.uniform(1.0, 2.0, 64)
        grads = np.stack(
            [base + 0.1 * rng.uniform(-1, 1, 64) for _ in range(2)]
        ).astype(np.float32)

        chans, engines = self._engines(23730, 2)
        try:
            def one(i):
                avg = engines[i].all_reduce(grads[i], op="mean")
                return host_noise_scale(engines[i], grads[i], avg, b_small)

            host_vals = run_all([lambda i=i: one(i) for i in range(2)])
        finally:
            for c in chans:
                c.close()
        # symmetric by construction (the inner mean is a collective)
        assert host_vals[0] == pytest.approx(host_vals[1], rel=1e-9)

        mesh = Mesh(np.array(jax.devices()[:2]), ("kf",))

        def gns_fn(g):
            avg = kc.all_reduce(g, "kf", op="mean")
            return global_noise_scale(g, avg, b_small, "kf")[None]

        got = shard_map(gns_fn, mesh=mesh, in_specs=P("kf"),
                        out_specs=P("kf"))(grads)
        in_graph = float(np.asarray(got)[0])
        assert host_vals[0] == pytest.approx(in_graph, rel=1e-3)


class TestMST:
    def test_chain(self):
        # latencies force a chain 0-1-2
        w = np.array([[0, 1, 10], [1, 0, 1], [10, 1, 0]], float)
        f = minimum_spanning_tree(w)
        assert f[0] == 0 and f[1] == 0 and f[2] == 1

    def test_star(self):
        w = np.array([[0, 1, 1, 1], [1, 0, 9, 9], [1, 9, 0, 9], [1, 9, 9, 0]], float)
        assert minimum_spanning_tree(w) == [0, 0, 0, 0]

    def test_asymmetric_symmetrized(self):
        w = np.array([[0, 2], [4, 0]], float)
        assert minimum_spanning_tree(w) == [0, 0]

    def test_bad_shape(self):
        with pytest.raises(ValueError):
            minimum_spanning_tree(np.zeros((2, 3)))


class TestAdaptIntegration:
    @pytest.fixture
    def peers(self):
        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.utils.envs import Config

        workers = PeerList.parse("127.0.0.1:27301,127.0.0.1:27302,127.0.0.1:27303")
        runners = PeerList.parse("127.0.0.1:38087")
        cluster = Cluster(runners, workers)
        ps = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
        for p in ps:
            p.start()
        yield ps
        for p in ps:
            p.close()


    def test_latencies(self, peers):
        lats = peers[0].get_peer_latencies()
        assert len(lats) == 3
        assert lats[0] == 0.0  # self
        assert lats[1] > 0 and lats[2] > 0

    def test_latency_matrix_and_mst(self, peers):
        from kungfu_tpu.monitor.adapt import latency_matrix

        mats = run_all([lambda p=p: latency_matrix(p) for p in peers])
        for m in mats:
            assert m.shape == (3, 3)
        f = minimum_spanning_tree(mats[0])
        assert len(f) == 3 and f[0] == 0

    def test_set_tree_then_allreduce(self, peers):
        chain = [0, 0, 1]  # explicit chain topology

        def one(p, val):
            p.set_tree(chain)
            out = p.engine().all_reduce(np.full(4, val, np.float32))
            return out

        outs = run_all([lambda p=p, v=v: one(p, float(v)) for v, p in enumerate(peers)])
        for o in outs:
            np.testing.assert_allclose(o, np.full(4, 3.0))  # 0+1+2

    def test_interference_vote(self, peers):
        # no throughput data -> no interference
        outs = run_all([lambda p=p: p.check_interference() for p in peers])
        assert outs == [False, False, False]

    def test_adaptive_driver_swaps_on_interference(self, peers):
        """Close the adaptation loop (reference adaptiveStrategies.go:
        57-121): establish a best-throughput window, throttle the network,
        and assert every rank swaps strategy in lockstep — with collectives
        still correct afterwards."""
        import time as _time

        from kungfu_tpu.monitor.adaptive import AdaptiveStrategyDriver
        from kungfu_tpu.plan import Strategy

        for p in peers:
            p.config.strategy = Strategy.STAR
        drivers = [
            AdaptiveStrategyDriver(p, check_every=1, min_steps_between_swaps=1)
            for p in peers
        ]
        data = np.ones(64_000, np.float32)  # big enough for a stable rate

        def train_step(p, d):
            out = p.engine().all_reduce(data, op="sum")
            swapped = d.step()
            return out, swapped

        # healthy step: establishes the reference window; the first check
        # can never flag (window == freshly-recorded best)
        outs = run_all([lambda p=p, d=d: train_step(p, d) for p, d in zip(peers, drivers)])
        assert not any(s for _, s in outs)

        # pin the recorded best far above anything this machine can do —
        # real wall-clock rates flap under parallel test load, so the
        # drop-below-0.8x condition is forced deterministically while the
        # suspicion -> majority vote -> fenced swap loop stays fully real
        for p in peers:
            e = p.engine()
            e.best_throughputs = [1e9] * len(e.best_throughputs)
        originals = []
        for p in peers:
            ch = p.channel
            orig = ch.send
            originals.append((ch, orig))

            def slow_send(*a, _orig=orig, **kw):
                _time.sleep(0.005)
                return _orig(*a, **kw)

            ch.send = slow_send
        try:
            swapped_anywhere = False
            for _ in range(3):
                outs = run_all(
                    [lambda p=p, d=d: train_step(p, d) for p, d in zip(peers, drivers)],
                    timeout=120,
                )
                for o, _ in outs:
                    np.testing.assert_allclose(o, data * 3)
                flags = [s for _, s in outs]
                assert len(set(flags)) == 1  # lockstep: all or none
                if flags[0]:
                    swapped_anywhere = True
                    break
            assert swapped_anywhere, "no swap despite sustained throttling"
            assert all(d.swaps == 1 for d in drivers)
            strategies = {p.engine().strategy for p in peers}
            assert strategies == {Strategy.BINARY_TREE_STAR}
        finally:
            for ch, orig in originals:
                ch.send = orig
        # post-swap collectives remain correct at full speed
        outs = run_all(
            [lambda p=p: p.engine().all_reduce(np.full(5, 2.0, np.float32)) for p in peers]
        )
        for o in outs:
            np.testing.assert_allclose(o, np.full(5, 6.0))

    def test_egress_rates_with_monitoring(self):
        import os

        from kungfu_tpu.peer import Peer
        from kungfu_tpu.plan import Cluster, PeerList
        from kungfu_tpu.utils.envs import Config

        os.environ["KF_CONFIG_ENABLE_MONITORING"] = "true"
        try:
            workers = PeerList.parse("127.0.0.1:27311,127.0.0.1:27312")
            cluster = Cluster(PeerList.parse("127.0.0.1:38088"), workers)
            ps = [Peer(Config(self_id=w, cluster=cluster)) for w in workers]
            for p in ps:
                p.start()
            try:
                engines = [p.engine() for p in ps]
                data = np.ones(1000, np.float32)
                run_all([lambda e=e: e.all_reduce(data) for e in engines])
                # native-backend egress arrives via the counter poll thread
                deadline = time.time() + 5
                while time.time() < deadline:
                    totals = ps[0].net_monitor.totals()
                    if sum(totals["egress"].values()) > 0:
                        break
                    time.sleep(0.2)
                assert sum(totals["egress"].values()) > 0
                assert len(ps[0].get_egress_rates()) == 2
                # /metrics endpoint is live at port+10000
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{27311 + 10000}/metrics", timeout=5
                ) as r:
                    assert b"kf_egress_bytes_total" in r.read()
            finally:
                for p in ps:
                    p.close()
        finally:
            os.environ.pop("KF_CONFIG_ENABLE_MONITORING", None)
