"""kf-sentinel tests: the durable history rings, the deterministic
detector math, the aggregator's judging plane (edge-triggered alerts +
incident flight records), the ``/alerts`` route, offline==online verdict
equality, and the disabled-path cost contract."""

import json
import os
import subprocess
import sys
import types
import urllib.error
import urllib.request

import pytest

from kungfu_tpu.monitor import detect, history, kfhist, timeline
from kungfu_tpu.monitor import sentinel as sentinellib
from kungfu_tpu.monitor.aggregator import (
    ClusterAggregator,
    RankReporter,
    field,
    make_snapshot,
)
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.monitor.sentinel import Sentinel, extract_series

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every KF_SENTINEL_* token (plus the shared serve-SLO budgets) — the
#: knob-parity tests must see a clean environment
_SENTINEL_ENVS = (
    "KF_SENTINEL_DIR", "KF_SENTINEL_KEEP_BYTES", "KF_SENTINEL_PERIOD",
    "KF_SENTINEL_WINDOW", "KF_SENTINEL_THRESHOLD", "KF_SENTINEL_MFU_FLOOR",
    "KF_SENTINEL_STEP_CEILING_S", "KF_SENTINEL_WARMUP_STEPS",
    "KF_SENTINEL_INCIDENT_WINDOW", "KF_SENTINEL_SLO_SHORT",
    "KF_SENTINEL_SLO_LONG", "KF_SERVE_SLO_TTFT_MS", "KF_SERVE_SLO_E2E_MS",
)


@pytest.fixture(autouse=True)
def _clean_sentinel_env(monkeypatch):
    for tok in _SENTINEL_ENVS:
        monkeypatch.delenv(tok, raising=False)


def _mesh(tmp_path, **kw):
    """Fake-clock aggregator + attached sentinel: one ingest per logical
    step, clock bumped 1 s after each, so exactly one sentinel sample
    lands per ingest (period_s=1.0) — deterministic cadence."""
    clock = [1000.0]
    agg = ClusterAggregator(stale_after=3600.0, time_fn=lambda: clock[0])
    kw.setdefault("window", 4)
    s = Sentinel(str(tmp_path), period_s=1.0, **kw)
    agg.attach_sentinel(s)
    return agg, s, clock


def _drive(agg, clock, step, step_time_s, **extra):
    agg.ingest(make_snapshot(rank=0, step=step, step_time_s=step_time_s,
                             wall=clock[0], **extra))
    clock[0] += 1.0


class TestDetect:
    def test_no_verdict_until_two_windows(self):
        assert detect.changepoint([0.1] * 7, window=4) is None
        assert detect.changepoint([0.1] * 8, window=4) is not None

    def test_clean_series_stays_flat(self):
        xs = [0.1 + (i % 5) * 1e-4 for i in range(32)]
        v = detect.changepoint(xs, window=8)
        assert v is not None and not v["shifted"] and v["direction"] == "flat"

    def test_planted_step_time_shift_detected_up(self):
        xs = [0.1] * 24 + [0.13] * 8  # a 30 ms regression on a 100 ms step
        v = detect.changepoint(xs, window=8)
        assert v["shifted"] and v["direction"] == "up"
        assert v["score"] >= v["threshold"]

    def test_detection_latency_within_two_windows(self):
        # feed the series one sample at a time, exactly how the online
        # plane accumulates: the planted shift must be called within
        # K=2 windows of its onset
        window, onset = 4, 16
        xs = [0.1] * onset
        fired_at = None
        for i in range(4 * window):
            xs.append(0.13)
            v = detect.changepoint(xs, window=window)
            if v and v["shifted"]:
                fired_at = i + 1
                break
        assert fired_at is not None and fired_at <= 2 * window

    def test_mfu_drop_is_direction_down(self):
        xs = [0.5] * 24 + [0.3] * 8
        v = detect.changepoint(xs, window=8)
        assert v["shifted"] and v["direction"] == "down"

    def test_tail_normalization_equality(self):
        # a caller holding MORE history must compute the identical
        # verdict — the offline==online equality rests on this
        xs = [0.1 + (i % 7) * 1e-3 for i in range(100)] + [0.2] * 8
        window = 8
        tail = xs[-(detect.BASELINE_WINDOWS + 1) * window:]
        assert detect.changepoint(xs, window=window) \
            == detect.changepoint(tail, window=window)

    def test_quiet_series_needs_relative_move(self):
        # MAD 0: a float-ulp wiggle must NOT alert (the rel_floor guard)
        xs = [1.0] * 24 + [1.0 + 1e-9] * 8
        v = detect.changepoint(xs, window=8)
        assert not v["shifted"]

    def test_burn_fraction_needs_full_window(self):
        assert detect.burn_fraction([900.0] * 3, 500.0, window=4) is None
        b = detect.burn_fraction([100.0, 900.0, 900.0, 100.0], 500.0,
                                 window=4)
        assert b["over"] == 2 and b["frac"] == 0.5

    def test_slo_burn_two_window_rule(self):
        # sustained burn: both windows over their fractions
        burn = detect.slo_burn([100.0] * 18 + [900.0] * 6, 500.0,
                               6, 24, 0.5, 0.25)
        assert burn["burning"]
        # one old blip: the short window is clean -> not burning
        burn = detect.slo_burn([100.0, 900.0] + [100.0] * 22, 500.0,
                               6, 24, 0.5, 0.25)
        assert not burn["burning"]

    def test_window_verdicts_drops_short_series(self):
        out = detect.window_verdicts(
            {"long": [0.1] * 16, "short": [0.1] * 3}, window=4)
        assert "long" in out and "short" not in out


class TestHistoryRing:
    def test_roundtrip_segmentation_and_order(self, tmp_path):
        d = str(tmp_path)
        ring = history.HistoryRing(d, "s", keep_bytes=1 << 20,
                                   segment_records=4)
        for i in range(10):
            ring.append({"i": i})
        # 10 appends at 4/segment: 2 sealed + 1 open file
        assert len(history._segments(d, "s")) == 3
        recs = history.read_stream(d, "s")
        assert [r["i"] for r in recs] == list(range(10))
        assert history.streams(d) == ["s"]
        # atomic rewrite discipline: no *.tmp orphan survives an append
        assert not [n for n in os.listdir(d) if n.endswith(".tmp")]

    def test_torn_line_skipped_not_fatal(self, tmp_path):
        d = str(tmp_path)
        ring = history.HistoryRing(d, "s", keep_bytes=1 << 20,
                                   segment_records=4)
        for i in range(8):
            ring.append({"i": i})
        segs = history._segments(d, "s")
        with open(segs[0][1], "ab") as f:
            f.write(b'{"torn": ')        # a crash mid-line
        with open(segs[1][1], "ab") as f:
            f.write(b"[1, 2, 3]\n")      # valid JSON, wrong shape
        recs, skipped = history.scan_stream(d, "s")
        assert [r["i"] for r in recs] == list(range(8))
        assert skipped == 2

    def test_gc_drops_oldest_sealed_only(self, tmp_path):
        d = str(tmp_path)
        ring = history.HistoryRing(d, "s", keep_bytes=40,
                                   segment_records=2)
        for i in range(10):
            ring.append({"i": i})
        recs = history.read_stream(d, "s")
        vals = [r["i"] for r in recs]
        # survivors are a strict SUFFIX: oldest dropped, newest kept
        assert 0 < len(vals) < 10
        assert vals == list(range(10))[-len(vals):]
        remaining = [seq for seq, _ in history._segments(d, "s")]
        assert remaining and remaining[0] > 0

    def test_gc_never_collects_open_segment(self, tmp_path):
        d = str(tmp_path)
        ring = history.HistoryRing(d, "s", keep_bytes=1,
                                   segment_records=100)
        for i in range(5):
            ring.append({"i": i})
        assert ring.gc() == 0
        assert len(history.read_stream(d, "s")) == 5

    def test_restart_opens_fresh_segment(self, tmp_path):
        d = str(tmp_path)
        a = history.HistoryRing(d, "s", keep_bytes=1 << 20,
                                segment_records=10)
        for i in range(3):
            a.append({"i": i})
        b = history.HistoryRing(d, "s", keep_bytes=1 << 20,
                                segment_records=10)
        # never appends into a predecessor's open file
        assert b._seq == a._seq + 1
        b.append({"i": 3})
        assert [r["i"] for r in history.read_stream(d, "s")] \
            == [0, 1, 2, 3]

    def test_bad_stream_name_rejected(self, tmp_path):
        for bad in ("", "a/b", ".hidden"):
            with pytest.raises(ValueError):
                history.HistoryRing(str(tmp_path), bad)


class TestExtractSeries:
    def test_rollup_fields(self):
        view = {
            "ranks": [
                {"rank": 0, "step": 7, "step_time_s": 0.1,
                 "counters": {"kf_jit_compiles_total": 3},
                 "gauges": {'kf_device_memory_bytes{kind="in_use"}': 5.0},
                 "net": {"egress_bytes": 10}},
                {"rank": 1, "step": 6, "step_time_s": 0.3,
                 "counters": {}, "gauges": {}, "net": {"egress_bytes": 2}},
            ],
            "xray": {"mfu": {"0": 0.2, "1": 0.4}, "phase_seconds":
                     {"compute": 1.5}},
            "serving": {"ttft_ms": 120.0, "e2e_ms": 900.0, "kv_bytes": 64},
        }
        s = extract_series(view)
        assert s["step_time_s"] == pytest.approx(0.2)
        assert s["step"] == 7.0 and s["egress_bytes"] == 12.0
        assert s["jit_compiles"] == 3.0 and s["device_mem_bytes"] == 5.0
        assert s["mfu"] == pytest.approx(0.3)
        assert s["phase_compute"] == 1.5
        assert s["ttft_ms"] == 120.0 and s["e2e_ms"] == 900.0

    def test_part_time_series_simply_absent(self):
        s = extract_series({"ranks": [{"rank": 0, "step": 1}]})
        assert "step_time_s" not in s and "mfu" not in s
        assert "egress_bytes" in s  # rows present -> net rollup present


class TestSentinelOnline:
    def test_no_false_positive_then_regression_alert(self, tmp_path):
        agg, s, clock = _mesh(tmp_path)
        for i in range(16):
            _drive(agg, clock, i, 0.1)
        assert s.alerts_view()["alerts"] == []      # clean phase silent
        fired_after = None
        for j in range(16):
            _drive(agg, clock, 16 + j, 0.25)
            fired = [a for a in s.alerts_view()["alerts"]
                     if a["rule"] == "regress:step_time_s"]
            if fired:
                fired_after = j + 1
                break
        # online detection within K=2 windows of the onset
        assert fired_after is not None and fired_after <= 2 * s.window
        # edge-triggered: the rule stays active but does not re-fire
        for j in range(4):
            _drive(agg, clock, 32 + j, 0.25)
        av = s.alerts_view()
        assert "regress:step_time_s" in av["active"]
        assert len([a for a in av["alerts"]
                    if a["rule"] == "regress:step_time_s"]) == 1

    def test_watermark_edge_refire_after_recovery(self, tmp_path):
        agg, s, clock = _mesh(tmp_path, step_ceiling_s=0.2)
        _drive(agg, clock, 0, 0.3)
        _drive(agg, clock, 1, 0.3)      # still over: no re-fire
        _drive(agg, clock, 2, 0.1)      # recovered
        _drive(agg, clock, 3, 0.3)      # fires again
        rules = [a["rule"] for a in s.alerts_view()["alerts"]]
        assert rules == ["watermark:step_time", "watermark:step_time"]

    def test_alert_ticks_counter_and_timeline(self, tmp_path):
        before = REGISTRY.counter("kf_alerts_total",
                                  rule="watermark:step_time").value
        agg, s, clock = _mesh(tmp_path, step_ceiling_s=0.2)
        _drive(agg, clock, 0, 0.5)
        after = REGISTRY.counter("kf_alerts_total",
                                 rule="watermark:step_time").value
        assert after == before + 1

    def test_incident_bundle_and_offline_replay_equality(self, tmp_path):
        agg, s, clock = _mesh(tmp_path)
        for i in range(16):
            _drive(agg, clock, i, 0.1)
        for j in range(8):
            _drive(agg, clock, 16 + j, 0.25)
        fired = [a for a in s.alerts_view()["alerts"]
                 if a["rule"] == "regress:step_time_s"]
        assert fired and fired[0]["incident"]
        with open(fired[0]["incident"], "r", encoding="utf-8") as f:
            bundle = json.load(f)
        assert bundle["kfincident"] == 1
        assert bundle["alert"]["rule"] == "regress:step_time_s"
        assert len(bundle["timeline_tail"]) <= sentinellib.INCIDENT_EVENT_TAIL
        assert "regress:step_time_s" in bundle["config"]["active_alerts"]
        # THE acceptance equality: kfhist --verdict --upto history_n over
        # the durable history reproduces the incident's verdicts exactly
        offline = kfhist.verdict_from_dir(
            str(tmp_path), upto=bundle["history_n"],
            window=s.window, threshold=s.threshold)
        assert json.dumps(offline["verdicts"], sort_keys=True) \
            == json.dumps(bundle["verdicts"], sort_keys=True)
        assert offline["verdicts"]["step_time_s"]["shifted"]
        # per-rank stream recorded alongside the cluster rollup
        assert "rank-0" in history.streams(str(tmp_path))

    def test_incident_timeline_tail_bounded(self, tmp_path):
        s = Sentinel(str(tmp_path), step_ceiling_s=0.2, window=4)
        view = {"wall": 1.0, "ranks": [{"rank": 0, "step": 0,
                                        "step_time_s": 0.5}]}
        events = [{"ts": float(i), "rank": 0, "kind": "collective",
                   "name": "engine.all_reduce", "dur": 0.001}
                  for i in range(400)]
        fired = s.observe(view, events)
        assert [a["rule"] for a in fired] == ["watermark:step_time"]
        with open(fired[0]["incident"], "r", encoding="utf-8") as f:
            bundle = json.load(f)
        assert len(bundle["timeline_tail"]) \
            == sentinellib.INCIDENT_EVENT_TAIL
        # the newest events are the ones kept
        assert bundle["timeline_tail"][-1]["ts"] == 399.0

    def test_sloburn_rule_fires_on_sustained_burn(self, tmp_path):
        s = Sentinel(str(tmp_path), window=4, slo_short=2, slo_long=4,
                     slo_budgets={"ttft_ms": 500.0})
        fired = []
        for i in range(4):
            fired = s.observe({"wall": float(i), "ranks": [],
                               "serving": {"ttft_ms": 900.0,
                                           "e2e_ms": 100.0,
                                           "kv_bytes": 0}})
        assert [a["rule"] for a in fired] == ["sloburn:ttft_ms"]
        assert fired[0]["evidence"]["burning"]

    def test_sloburn_silent_on_single_blip(self, tmp_path):
        s = Sentinel(str(tmp_path), window=4, slo_short=2, slo_long=4,
                     slo_budgets={"ttft_ms": 500.0})
        for i, v in enumerate([100.0, 900.0, 100.0, 100.0]):
            fired = s.observe({"wall": float(i), "ranks": [],
                               "serving": {"ttft_ms": v, "e2e_ms": 100.0,
                                           "kv_bytes": 0}})
            assert fired == []

    def test_watermark_mfu_floor(self, tmp_path):
        s = Sentinel(str(tmp_path), mfu_floor=0.3, window=4)
        view = {"wall": 1.0, "ranks": [],
                "xray": {"mfu": {"0": 0.2}, "phase_seconds": {}}}
        fired = s.observe(view)
        assert [a["rule"] for a in fired] == ["watermark:mfu"]
        assert s.observe(view) == []    # edge-triggered

    def test_watermark_stale_slice(self, tmp_path):
        s = Sentinel(str(tmp_path), window=4)
        fired = s.observe({"wall": 1.0, "ranks": [], "stale_slices": [1]})
        assert [a["rule"] for a in fired] == ["watermark:stale_slice"]
        assert fired[0]["evidence"]["slices"] == [1]

    def test_watermark_ckpt_age(self, tmp_path):
        s = Sentinel(str(tmp_path), window=4)
        row = {"rank": 2, "step": 5, "step_time_s": 0.1,
               "gauges": {"kf_ckpt_period_seconds": 10.0,
                          "kf_ckpt_age_seconds": 40.0}}
        fired = s.observe({"wall": 1.0, "ranks": [row]})
        assert [a["rule"] for a in fired] == ["watermark:ckpt_age"]
        assert fired[0]["evidence"]["ranks"][0]["rank"] == 2

    def test_watermark_recompile_steady(self, tmp_path):
        s = Sentinel(str(tmp_path), warmup_steps=4, window=4)

        def view(step, compiles):
            return {"wall": float(step), "ranks": [
                {"rank": 0, "step": step, "step_time_s": 0.1,
                 "counters": {"kf_jit_compiles_total": compiles}}]}

        assert s.observe(view(2, 10)) == []   # warmup: compiles are free
        assert s.observe(view(5, 3)) == []    # baseline pinned here
        assert s.observe(view(6, 3)) == []    # steady: no growth
        fired = s.observe(view(7, 4))         # a post-warmup recompile
        assert [a["rule"] for a in fired] == ["watermark:recompile_steady"]
        assert fired[0]["evidence"]["baseline"] == 3.0


class TestDisabledPath:
    def test_from_env_none_without_dir(self):
        assert Sentinel.from_env() is None

    def test_from_env_parses_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KF_SENTINEL_DIR", str(tmp_path))
        monkeypatch.setenv("KF_SENTINEL_WINDOW", "5")
        monkeypatch.setenv("KF_SENTINEL_THRESHOLD", "6.5")
        s = Sentinel.from_env()
        assert s is not None and s.window == 5 and s.threshold == 6.5
        assert s.root == str(tmp_path)
        assert s.period_s == sentinellib.DEFAULT_PERIOD_S

    def test_disabled_aggregator_byte_identical(self, tmp_path):
        # the cost contract: attaching a sentinel only ADDS the alerts
        # section; with no sentinel the view and the prometheus render
        # are byte-identical to the pre-sentinel plane
        clock = [1000.0]
        plain = ClusterAggregator(stale_after=3600.0,
                                  time_fn=lambda: clock[0])
        judged = ClusterAggregator(stale_after=3600.0,
                                   time_fn=lambda: clock[0])
        judged.attach_sentinel(Sentinel(str(tmp_path), window=4))
        for agg in (plain, judged):
            for i in range(4):
                agg.ingest(make_snapshot(rank=0, step=i, step_time_s=0.1,
                                         wall=clock[0]))
        assert plain._sentinel is None
        va, vb = plain.cluster_view(), judged.cluster_view()
        assert "alerts" not in va and "alerts" in vb
        vb = {k: v for k, v in vb.items() if k != "alerts"}
        assert json.dumps(va, sort_keys=True) \
            == json.dumps(vb, sort_keys=True)
        assert "kf_cluster_alerts_active" not in plain.render_prometheus()
        assert "kf_cluster_alerts_active" in judged.render_prometheus()


class TestKnobParity:
    def test_env_tokens_shared(self):
        from kungfu_tpu.utils import envs
        assert envs.SENTINEL_DIR == history.DIR_ENV
        assert envs.SENTINEL_KEEP_BYTES == history.KEEP_BYTES_ENV
        assert envs.SENTINEL_WINDOW == sentinellib.WINDOW_ENV
        assert envs.SENTINEL_THRESHOLD == sentinellib.THRESHOLD_ENV
        assert envs.SERVE_SLO_TTFT_MS == sentinellib.TTFT_BUDGET_ENV
        assert envs.SERVE_SLO_E2E_MS == sentinellib.E2E_BUDGET_ENV

    def test_sentinel_knob_defaults_pinned(self):
        # envs.sentinel_knobs() and the monitor/sentinel.py mirror
        # constants must agree (the stubbed kfhist context reads the
        # mirrors; kfrun reads envs) — the documented contract
        from kungfu_tpu.utils import envs
        k = envs.sentinel_knobs()
        assert k["dir"] == ""
        assert k["keep_bytes"] == history.DEFAULT_KEEP_BYTES
        assert k["period_s"] == sentinellib.DEFAULT_PERIOD_S
        assert k["window"] == detect.DEFAULT_WINDOW
        assert k["threshold"] == detect.DEFAULT_THRESHOLD
        assert k["warmup_steps"] == sentinellib.DEFAULT_WARMUP_STEPS
        assert k["incident_window"] == sentinellib.DEFAULT_INCIDENT_WINDOW
        assert k["slo_short"] == sentinellib.DEFAULT_SLO_SHORT
        assert k["slo_long"] == sentinellib.DEFAULT_SLO_LONG

    def test_slo_rules_defaults_pinned(self):
        from kungfu_tpu.serve.slo import SLORules
        r = SLORules()
        assert r.ttft_budget_ms == sentinellib.DEFAULT_TTFT_BUDGET_MS
        assert r.e2e_budget_ms == sentinellib.DEFAULT_E2E_BUDGET_MS
        assert r.short_window == sentinellib.DEFAULT_SLO_SHORT
        assert r.long_window == sentinellib.DEFAULT_SLO_LONG
        assert r.short_frac == sentinellib.DEFAULT_SLO_SHORT_FRAC
        assert r.long_frac == sentinellib.DEFAULT_SLO_LONG_FRAC


class TestAlertsRoute:
    @pytest.fixture
    def server(self):
        from kungfu_tpu.elastic.configserver import ConfigServer
        from kungfu_tpu.plan import Cluster, PeerList

        workers = PeerList.parse(
            "127.0.0.1:27431,127.0.0.1:27432,127.0.0.1:27433")
        cluster = Cluster(PeerList.parse("127.0.0.1:38093"), workers)
        agg = ClusterAggregator(stale_after=60.0)
        srv = ConfigServer(port=0, cluster=cluster, aggregator=agg).start()
        yield srv, agg, f"http://127.0.0.1:{srv.port}"
        srv.stop()

    def test_alerts_route_404_then_200(self, server, tmp_path):
        srv, agg, base = server
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/alerts", timeout=5)
        assert ei.value.code == 404
        agg.attach_sentinel(Sentinel(str(tmp_path), window=4,
                                     step_ceiling_s=0.2))
        agg.ingest(make_snapshot(rank=0, step=1, step_time_s=0.5))
        with urllib.request.urlopen(base + "/alerts", timeout=5) as resp:
            payload = json.loads(resp.read().decode())
        assert payload["kfsentinel"] == 1
        assert "watermark:step_time" in payload["active"]
        assert payload["alerts"][0]["rule"] == "watermark:step_time"
        # the /cluster view carries the same section
        with urllib.request.urlopen(base + "/cluster", timeout=5) as resp:
            view = json.loads(resp.read().decode())
        assert field(view, "alerts")["active"] == payload["active"]


class TestKftopAlerts:
    def test_render_view_alerts_section(self, tmp_path):
        from kungfu_tpu.monitor import kftop

        agg, s, clock = _mesh(tmp_path, step_ceiling_s=0.2)
        _drive(agg, clock, 0, 0.5)
        text = kftop.render_view(agg.cluster_view())
        assert "== ALERTS" in text and "watermark:step_time" in text

    def test_render_view_no_section_without_sentinel(self):
        from kungfu_tpu.monitor import kftop

        agg = ClusterAggregator(stale_after=60.0)
        agg.ingest(make_snapshot(rank=0, step=1, step_time_s=0.1))
        assert "== ALERTS" not in kftop.render_view(agg.cluster_view())


class TestPolicySignals:
    def test_signals_from_alerts_payload(self, tmp_path):
        from kungfu_tpu.policy import sentinel_signals

        s = Sentinel(str(tmp_path), window=4, step_ceiling_s=0.2)
        s.observe({"wall": 1.0, "ranks": [{"rank": 0, "step": 0,
                                           "step_time_s": 0.5}]})
        sig = sentinel_signals(s.alerts_view())
        assert sig["firing"] and sig["watermarks"] == ["step_time"]
        assert sig["fired_total"] == 1
        # plane off: None, distinguishable from "no alerts"
        assert sentinel_signals({"ranks": []}) is None


class TestReporterHooks:
    def test_pre_snapshot_fn_exception_guarded(self):
        def boom():
            raise RuntimeError("gauge poll failed")

        rep = RankReporter(0, "http://127.0.0.1:1/get",
                           pre_snapshot_fn=boom)
        snap = rep.snapshot_once()     # must not raise
        assert field(snap, "rank") == 0

    def test_publish_device_memory_none_safe(self):
        from kungfu_tpu.monitor.metrics import publish_device_memory

        assert isinstance(publish_device_memory(), bool)

    def test_install_compile_metrics_idempotent_and_ticks(self):
        from kungfu_tpu.utils import jaxcompat

        ok = jaxcompat.install_compile_metrics()
        assert jaxcompat.install_compile_metrics() is ok
        if ok:
            import jax
            import numpy as np

            before = REGISTRY.counter("kf_jit_compiles_total").value
            jax.jit(lambda x: x * 2 + 1)(np.arange(7, dtype="float32"))
            assert REGISTRY.counter("kf_jit_compiles_total").value > before


class TestChaosAfterStep:
    def test_parse_after_step(self):
        from kungfu_tpu.chaos.spec import parse_spec

        c = parse_spec("delay:ms=5,rank=0,peer=1,after_step=16")[0]
        assert c.kind == "delay" and c.get("after_step") == 16

    def test_clause_inert_until_armed(self):
        from kungfu_tpu.chaos.inject import ChaosController
        from kungfu_tpu.chaos.spec import parse_spec

        clauses = parse_spec("delay:ms=0,after_step=3")
        ctl = ChaosController(clauses, rank=0, seed=1)
        ctl.on_send(1, "t", b"")
        ctl.on_step(2)
        ctl.on_send(1, "t", b"")
        assert ctl._matched == {}          # inert: nothing counted
        ctl.on_step(3)
        ctl.on_send(1, "t", b"")
        assert ctl._matched == {0: 1}      # armed: events count now

    def test_every_strides_armed_events_only(self):
        from kungfu_tpu.chaos.inject import ChaosController
        from kungfu_tpu.chaos.spec import parse_spec

        clauses = parse_spec("delay:ms=0,every=2,after_step=1")
        ctl = ChaosController(clauses, rank=0, seed=1)
        for _ in range(5):                  # pre-onset traffic is free
            ctl.on_send(1, "t", b"")
        ctl.on_step(1)
        for _ in range(2):
            ctl.on_send(1, "t", b"")
        # the every=2 stride counts from the ONSET, not process start
        assert ctl._matched == {0: 2}


class TestScripts:
    def _run(self, script, *args):
        return subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", script), *args],
            capture_output=True, text=True, timeout=120)

    def test_kfhist_self_check(self):
        r = self._run("kfhist", "--self-check")
        assert r.returncode == 0, r.stderr
        assert "self-check ok" in r.stdout

    def test_kfhist_cli_list_and_verdict(self, tmp_path):
        ring = history.HistoryRing(str(tmp_path), "cluster",
                                   keep_bytes=1 << 20, segment_records=8)
        for i in range(24):
            st = 0.1 if i < 16 else 0.25
            ring.append({"kfhist": 1, "wall": float(i),
                         "series": {"step_time_s": st}})
        r = self._run("kfhist", "--dir", str(tmp_path), "--list", "--json")
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout)["cluster"]["records"] == 24
        r = self._run("kfhist", "--dir", str(tmp_path), "--verdict",
                      "--window", "4", "--json")
        assert r.returncode == 0, r.stderr
        v = json.loads(r.stdout)["verdicts"]["step_time_s"]
        assert v["shifted"] and v["direction"] == "up"

    def test_kfbench_diff_self_check(self):
        r = self._run("kfbench-diff", "--self-check")
        assert r.returncode == 0, r.stderr

    def test_checked_in_bench_baseline_current(self):
        # the benchdiff gate must hold against the committed artifacts
        r = self._run("kfbench-diff",
                      os.path.join(ROOT, "tests", "bench_baseline.json"),
                      os.path.join(ROOT, "BENCH_extra.json"))
        assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
class TestLiveMesh:
    def test_three_rank_offline_online_equality(self):
        # the full acceptance drill (also the check.sh sentinel-gate):
        # 3-rank paced training mesh, chaos delays armed mid-run via
        # after_step, online alert within K windows, incident names the
        # planted rank, kfhist replay identical to the incident verdicts
        sys.path.insert(0, ROOT)
        try:
            import bench
            row = bench.payload_sentinel(types.SimpleNamespace(quick=True))
        finally:
            sys.path.remove(ROOT)
        assert row["vs_baseline"] == 1.0, row["checks"]
        assert all(row["checks"].values()), row["checks"]
