"""kf-verify geometry coverage in tier-1: the shipped tree proves clean
over every ParallelPlan geometry the sweep enumerates, the simulator's
tag model is pinned to the extracted sites (and to the engine's op
table), and seeded protocol mutations are caught.

The bad-fixture exact-line pins live in tests/test_lint.py; this file
owns the whole-tree / whole-geometry properties and the drift pins.
"""

import os

from kungfu_tpu.analysis import callgraph, commgraph, core, protoverify
from kungfu_tpu.analysis.core import repo_root

ROOT = repo_root(os.path.dirname(os.path.abspath(__file__)))

VERIFY_ENVS = ("KF_VERIFY_MAX_RANKS", "KF_VERIFY_GEOMETRY_CAP",
               "KF_VERIFY_TIMEOUT_S")


def _fresh_caches():
    core.clear_parse_cache()
    callgraph.invalidate_cache()


def _zero_tree(tmp_path, mutate):
    """A minimal tree carrying a (mutated) copy of the shipped zero.py;
    the pipeline entry is absent so only the static rules run."""
    pkg = tmp_path / "kungfu_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "kungfu_tpu" / "__init__.py").write_text("\n")
    (pkg / "__init__.py").write_text("\n")
    src = open(os.path.join(ROOT, "kungfu_tpu", "parallel", "zero.py"),
               encoding="utf-8").read()
    mutated = mutate(src)
    assert mutated != src, "mutation did not apply — needle drifted"
    (pkg / "zero.py").write_text(mutated)
    _fresh_caches()
    return str(tmp_path)


class TestGeometrySweep:
    def test_every_shipped_geometry_verifies_clean(self, monkeypatch):
        """THE acceptance property: zero findings across 1F1B /
        interleaved / sequential schedules, the ZeRO bucket loops, both
        recarve protocols, the ring mirrors and the serve replay path,
        for every valid geometry up to 16 ranks (defaults pinned)."""
        for k in VERIFY_ENVS:
            monkeypatch.delenv(k, raising=False)
        got = protoverify.check(ROOT)
        assert got == [], "\n".join(v.render() for v in got)

    def test_all_entrypoints_extracted(self):
        _, entries, viols = commgraph.entry_protocols(ROOT)
        assert viols == [], [v.render() for v in viols]
        names = {e.name for e in entries}
        expect = {
            "kungfu_tpu.parallel.zero::host_bucket_pipeline",
            "kungfu_tpu.parallel.zero::host_bucket_all_gather",
            "kungfu_tpu.parallel.pp::HostPipeline.train_step",
            "kungfu_tpu.parallel.pp::StageBoundary.replicate_ring",
            "kungfu_tpu.parallel.pp::StageBoundary.recarve",
            "kungfu_tpu.elastic.reshard::ZeroBoundary.replicate_ring",
            "kungfu_tpu.elastic.reshard::ZeroBoundary._recarve_channel",
            "kungfu_tpu.serve.router::ServeRouter._dispatch",
            "kungfu_tpu.serve.router::ServeRouter._replay",
        }
        missing = expect - names
        assert not missing, f"entrypoints lost from extraction: {missing}"


class TestModelPins:
    def test_engine_spec_table_matches_fallback(self):
        """COMM_OP_SPECS in comm/engine.py IS the verifier's op model;
        the stdlib-only fallback (fixture trees) must stay identical."""
        specs, viols = commgraph.engine_specs(ROOT)
        assert viols == [], [v.render() for v in viols]
        assert specs == commgraph.FALLBACK_SPECS

    def test_knob_defaults_pinned_to_registry(self, monkeypatch):
        """protoverify reads os.environ directly (it cannot import the
        jax-adjacent registry); both sides must agree on defaults."""
        for k in VERIFY_ENVS:
            monkeypatch.delenv(k, raising=False)
        from kungfu_tpu.utils import envs
        assert envs.verify_knobs() == {
            "max_ranks": protoverify.DEFAULT_MAX_RANKS,
            "geometry_cap": protoverify.DEFAULT_GEOMETRY_CAP,
            "timeout_s": protoverify.DEFAULT_TIMEOUT_S,
        }
        assert protoverify._knobs() == (
            protoverify.DEFAULT_MAX_RANKS,
            protoverify.DEFAULT_GEOMETRY_CAP,
            protoverify.DEFAULT_TIMEOUT_S,
        )

    def test_knob_env_overrides_respected(self, monkeypatch):
        monkeypatch.setenv("KF_VERIFY_MAX_RANKS", "8")
        monkeypatch.setenv("KF_VERIFY_GEOMETRY_CAP", "100")
        monkeypatch.setenv("KF_VERIFY_TIMEOUT_S", "5.5")
        assert protoverify._knobs() == (8, 100, 5.5)
        from kungfu_tpu.utils import envs
        assert envs.verify_knobs() == {
            "max_ranks": 8, "geometry_cap": 100, "timeout_s": 5.5}

    def test_window_bound_constants_hold(self):
        """The bound pp.py enforces at construction (and proto-verify
        pins statically), checked here against the shipped constants."""
        from kungfu_tpu.comm.engine import ASYNC_POOL_WORKERS
        from kungfu_tpu.parallel.pp import _MAX_INFLIGHT_SENDS, _PREFETCH
        assert _PREFETCH + _MAX_INFLIGHT_SENDS + 2 <= ASYNC_POOL_WORKERS


class TestSeededMutations:
    def test_uniform_bucket_swap_caught(self, tmp_path):
        """Swapping the bucket reduce-scatter order uniformly on every
        rank is invisible to cross-rank comparison — the canonical-order
        rule must catch the b{N-1-i} tag statically."""
        root = _zero_tree(tmp_path, lambda s: s.replace(
            'name=f"{name}.b{i}"',
            'name=f"{name}.b{len(spans) - 1 - i}"', 1))
        try:
            got = protoverify.check(root)
            assert got, "mutated bucket order not detected"
            assert all("canonical" in v.message for v in got), \
                [v.render() for v in got]
            assert all(v.path.endswith("zero.py") for v in got)
        finally:
            _fresh_caches()

    def test_reversed_bucket_loop_caught(self, tmp_path):
        root = _zero_tree(tmp_path, lambda s: s.replace(
            "for i in range(len(spans))]",
            "for i in reversed(range(len(spans)))]", 1))
        try:
            got = protoverify.check(root)
            assert got, "reversed bucket loop not detected"
            assert any("reversed" in v.message for v in got), \
                [v.render() for v in got]
        finally:
            _fresh_caches()
