"""kf-overlap: async collective handles, the bounded in-flight window,
the host-plane bucket pipeline, and the learnable depth arm.

The invariants these tests pin:

* async results are bitwise the sync results (same wire protocol, same
  tags — sync and async issuers can even rendezvous with each other);
* the in-flight window bounds concurrency at ``overlap_depth`` and
  issuing past it blocks until a completion frees a slot;
* serial and pipelined bucket loops produce bitwise-identical results
  (the one-geometry invariant extended to time);
* drain settles everything and the ``kf_overlap_inflight`` gauge
  returns to 0 — the no-leaked-handles criterion.
"""

import threading
import time

import numpy as np
import pytest

from kungfu_tpu.comm.engine import CollectiveEngine
from kungfu_tpu.comm.host import HostChannel
from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.parallel.zero import (host_bucket_all_gather,
                                      host_bucket_pipeline,
                                      host_bucket_spans)
from kungfu_tpu.plan import Strategy
from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.policy.bandit import OverlapDepthBandit

from _util import run_all


def make_engines(n, base_port, strategy=Strategy.STAR):
    peers = PeerList.of(*(PeerID("127.0.0.1", base_port + i)
                          for i in range(n)))
    chans = [HostChannel(p, bind_host="127.0.0.1") for p in peers]
    engines = [CollectiveEngine(c, peers, strategy) for c in chans]
    return peers, chans, engines


def close_all(chans, engines=()):
    for e in engines:
        e.close()
    for c in chans:
        c.close()


def inflight_gauge():
    return REGISTRY.snapshot().get("kf_overlap_inflight", 0.0)


class TestAsyncHandles:
    def test_async_matches_sync_bitwise(self):
        peers, chans, engines = make_engines(2, 27700)
        data = [np.arange(256, dtype=np.float32) * (i + 1) for i in range(2)]
        try:
            def sync(i):
                return engines[i].all_reduce(data[i], name="s")

            def async_(i):
                h = engines[i].all_reduce_async(data[i], name="a")
                assert h.wait(timeout=30) is not None
                return h.wait(timeout=1)  # idempotent re-wait

            got_s = run_all([lambda i=i: sync(i) for i in range(2)])
            got_a = run_all([lambda i=i: async_(i) for i in range(2)])
            for s, a in zip(got_s, got_a):
                assert np.array_equal(s, a)
                assert np.array_equal(s, data[0] + data[1])
        finally:
            close_all(chans, engines)

    def test_sync_and_async_issuers_rendezvous(self):
        """The wire protocol is identical: rank 0 issues async, rank 1
        sync, same explicit tag — they still rendezvous."""
        peers, chans, engines = make_engines(2, 27710)
        data = [np.ones(32, np.float32) * (i + 1) for i in range(2)]
        try:
            def r0():
                h = engines[0].all_reduce_async(data[0], name="mix")
                return h.wait(timeout=30)

            def r1():
                return engines[1].all_reduce(data[1], name="mix")

            outs = run_all([r0, r1])
            for o in outs:
                assert np.array_equal(o, data[0] + data[1])
        finally:
            close_all(chans, engines)

    def test_reduce_scatter_and_all_gather_async(self):
        peers, chans, engines = make_engines(2, 27720)
        data = [np.arange(64, dtype=np.float32) * (i + 1) for i in range(2)]
        try:
            def rs(i):
                return engines[i].reduce_scatter_async(
                    data[i], name="rs1").wait(timeout=30)

            outs = run_all([lambda i=i: rs(i) for i in range(2)])
            want = data[0] + data[1]
            assert np.array_equal(outs[0], want[:32])
            assert np.array_equal(outs[1], want[32:])

            def ag(i):
                return engines[i].all_gather_async(
                    outs[i], name="ag1").wait(timeout=30)

            full = run_all([lambda i=i: ag(i) for i in range(2)])
            for f in full:
                assert np.array_equal(f.reshape(-1), want)
        finally:
            close_all(chans, engines)

    def test_window_bounds_inflight_and_blocks(self):
        """Issuing past overlap_depth blocks until a completion frees a
        slot — observed via a deliberately slow peer 1."""
        peers, chans, engines = make_engines(2, 27730)
        try:
            engines[0].set_overlap_depth(2)
            release = threading.Event()
            seen_depth = []

            def r1():
                # rank 1 participates late: rank 0's handles stay in
                # flight until this side shows up
                release.wait(20)
                for k in range(3):
                    engines[1].all_reduce(np.ones(8, np.float32),
                                          name=f"w{k}")

            def r0():
                h0 = engines[0].all_reduce_async(np.ones(8, np.float32),
                                                 name="w0")
                h1 = engines[0].all_reduce_async(np.ones(8, np.float32),
                                                 name="w1")
                seen_depth.append(engines[0].inflight())
                t0 = time.perf_counter()

                def unblock():
                    time.sleep(0.3)
                    release.set()

                threading.Thread(target=unblock, daemon=True).start()
                # third issue must BLOCK until a slot frees (rank 1 only
                # starts answering after release fires)
                h2 = engines[0].all_reduce_async(np.ones(8, np.float32),
                                                 name="w2")
                blocked = time.perf_counter() - t0
                for h in (h0, h1, h2):
                    h.wait(timeout=30)
                return blocked

            blocked, _ = run_all([r0, r1])
            assert seen_depth == [2]
            assert blocked >= 0.25, f"issue did not block ({blocked:.3f}s)"
            assert engines[0].inflight() == 0
        finally:
            close_all(chans, engines)

    def test_depth_retune_wakes_blocked_issuer(self):
        peers, chans, engines = make_engines(2, 27740)
        try:
            engines[0].set_overlap_depth(1)
            started = threading.Event()

            def r0():
                h0 = engines[0].all_reduce_async(np.ones(4, np.float32),
                                                 name="d0")
                started.set()
                # blocks at depth 1; the retune to 2 admits it while d0
                # is still unanswered
                h1 = engines[0].all_reduce_async(np.ones(4, np.float32),
                                                 name="d1")
                assert engines[0].overlap_depth == 2
                return [h0.wait(timeout=30), h1.wait(timeout=30)]

            def retuner():
                started.wait(10)
                time.sleep(0.2)
                engines[0].set_overlap_depth(2)

            def r1():
                started.wait(10)
                time.sleep(0.4)  # after the retune admitted d1
                for k in range(2):
                    engines[1].all_reduce(np.ones(4, np.float32),
                                          name=f"d{k}")

            run_all([r0, retuner, r1])
            with pytest.raises(ValueError):
                engines[0].set_overlap_depth(0)
        finally:
            close_all(chans, engines)

    def test_drain_and_gauge_return_to_zero(self):
        peers, chans, engines = make_engines(2, 27750)
        try:
            def r0():
                h = engines[0].all_reduce_async(np.ones(16, np.float32),
                                                name="g0")
                drained = engines[0].drain_async()
                assert drained == 1
                assert engines[0].inflight() == 0
                # drain settles but does NOT consume: the owner still
                # observes the result at wait()
                return h.wait(timeout=5)

            def r1():
                return engines[1].all_reduce(np.ones(16, np.float32),
                                             name="g0")

            outs = run_all([r0, r1])
            assert np.array_equal(outs[0], outs[1])
            assert inflight_gauge() == 0.0
            assert engines[0].drain_async() == 0  # empty drain is free
        finally:
            close_all(chans, engines)

    def test_efficiency_histogram_observed(self):
        peers, chans, engines = make_engines(2, 27760)
        try:
            before = REGISTRY.snapshot().get("kf_overlap_efficiency",
                                             {"count": 0})["count"]

            def r(i):
                h = engines[i].all_reduce_async(np.ones(8, np.float32),
                                                name="e0")
                time.sleep(0.05)  # give the wire a head start
                return h.wait(timeout=30)

            run_all([lambda i=i: r(i) for i in range(2)])
            after = REGISTRY.snapshot()["kf_overlap_efficiency"]["count"]
            assert after == before + 2
        finally:
            close_all(chans, engines)

    def test_failed_handle_does_not_observe_efficiency(self, monkeypatch):
        """A doomed handle waited on late would read as 'wire fully
        hidden' — failed collectives must stay out of the histogram."""
        monkeypatch.setenv("KF_CONFIG_PEER_DEADLINE", "1")
        peers, chans, engines = make_engines(2, 27765)
        chans[1].close()  # rank 1 is dead before the collective
        try:
            before = REGISTRY.snapshot().get("kf_overlap_efficiency",
                                             {"count": 0})["count"]
            h = engines[0].all_reduce_async(np.ones(8, np.float32),
                                            name="dead")
            time.sleep(1.5)  # settle via the deadline, then wait "late"
            from kungfu_tpu.comm.faults import PeerFailureError

            with pytest.raises(PeerFailureError):
                h.wait(timeout=10)
            after = REGISTRY.snapshot()["kf_overlap_efficiency"]["count"]
            assert after == before
        finally:
            close_all([chans[0]], [engines[0]])
            engines[1].close()

    def test_latency_hook_fed_per_completion(self):
        peers, chans, engines = make_engines(2, 27770)
        fed = []
        try:
            engines[0].set_latency_hook(
                lambda nbytes, depth, dt: fed.append((nbytes, depth, dt)))

            def r(i):
                return engines[i].all_reduce_async(
                    np.ones(100, np.float32), name="h0").wait(timeout=30)

            run_all([lambda i=i: r(i) for i in range(2)])
            assert len(fed) == 1
            nbytes, depth, dt = fed[0]
            assert nbytes == 400 and depth == engines[0].overlap_depth
            assert dt > 0
            engines[0].set_latency_hook(None)
        finally:
            close_all(chans, engines)

    def test_issue_complete_timeline_events(self, monkeypatch):
        monkeypatch.setenv("KF_CONFIG_ENABLE_TRACE", "1")
        timeline.reset()
        peers, chans, engines = make_engines(2, 27780)
        try:
            def r(i):
                return engines[i].all_reduce_async(
                    np.ones(8, np.float32), name="tl0").wait(timeout=30)

            run_all([lambda i=i: r(i) for i in range(2)])
            evs = [e for e in timeline.snapshot() if e["kind"] == "overlap"]
            names = sorted(e["name"] for e in evs)
            assert names == ["complete", "complete", "issue", "issue"], evs
            for e in evs:
                assert e["attrs"]["tag"] == "tl0"
                assert e["attrs"]["nbytes"] == 32
                assert "inflight" in e["attrs"]
        finally:
            close_all(chans, engines)
            timeline.reset()


class TestHostBucketPipeline:
    N = 3
    CHUNK = 48
    WIDTHS = [20, 20, 8]

    def _flats(self):
        rng = np.random.default_rng(7)
        return [rng.standard_normal(self.N * self.CHUNK).astype(np.float32)
                for _ in range(self.N)]

    def test_spans_must_tile_chunk(self):
        assert host_bucket_spans(10, [4, 6]) == [(0, 4), (4, 6)]
        with pytest.raises(ValueError):
            host_bucket_spans(10, [4, 4])

    @pytest.mark.parametrize("widths", [[48], [20, 20, 8], [1] * 48])
    def test_serial_vs_pipelined_bitwise(self, widths):
        """THE overlap invariant: pipelining moves wall clock only —
        per-bucket results are byte-equal to the serial loop for every
        bucket count including the degenerate single bucket."""
        peers, chans, engines = make_engines(self.N, 27800)
        flats = self._flats()
        try:
            def run(i, pipelined, tag):
                return host_bucket_pipeline(
                    engines[i], flats[i], widths,
                    lambda b, red: red * np.float32(0.5) + b,
                    pipelined=pipelined, name=tag)

            srl = run_all([lambda i=i: run(i, False, "s") for i in range(self.N)])
            pip = run_all([lambda i=i: run(i, True, "p") for i in range(self.N)])
            want = sum(flats).reshape(self.N, self.CHUNK)
            for i in range(self.N):
                a = np.concatenate(srl[i])
                b = np.concatenate(pip[i])
                assert a.tobytes() == b.tobytes()
                # external reference to allclose only: the engine's graph
                # reduction order differs from numpy's left-fold in the
                # last ulp — the BITWISE claim is serial-vs-pipelined
                ref = np.concatenate([
                    want[i, off:off + w] * np.float32(0.5) + bi
                    for bi, (off, w) in
                    enumerate(host_bucket_spans(self.CHUNK, widths))])
                assert np.allclose(a, ref, rtol=1e-5, atol=1e-6)
            assert inflight_gauge() == 0.0
        finally:
            close_all(chans, engines)

    def test_compute_runs_while_next_bucket_flies(self):
        """The pipeline's point, observed directly: with compute that
        takes real time, at least one later bucket completes its wire
        time BEFORE an earlier bucket's compute finished."""
        peers, chans, engines = make_engines(2, 27820)
        n, chunk = 2, 40
        widths = [10, 10, 10, 10]
        flats = [np.ones(n * chunk, np.float32) for _ in range(2)]
        overlap_seen = []
        try:
            def compute(b, red):
                time.sleep(0.05)
                return red

            def run(i):
                return host_bucket_pipeline(
                    engines[i], flats[i], widths, compute,
                    pipelined=True, depth=2, name="ov")

            t0 = time.perf_counter()
            run_all([lambda i=i: run(i) for i in range(2)])
            elapsed = time.perf_counter() - t0
            # serial lower bound would be 4 computes + 4 wire RTTs in
            # series; pipelined must at least hide wire under compute
            overlap_seen.append(elapsed)
            assert elapsed < 1.0
        finally:
            close_all(chans, engines)

    def test_all_gather_pipeline_matches_serial(self):
        peers, chans, engines = make_engines(self.N, 27840)
        shards = [np.arange(self.CHUNK, dtype=np.float32) * (i + 1)
                  for i in range(self.N)]
        try:
            def run(i, pipelined, tag):
                return host_bucket_all_gather(
                    engines[i], shards[i], self.WIDTHS,
                    pipelined=pipelined, name=tag)

            srl = run_all([lambda i=i: run(i, False, "as")
                           for i in range(self.N)])
            pip = run_all([lambda i=i: run(i, True, "ap")
                           for i in range(self.N)])
            want = np.concatenate([
                np.stack([s[off:off + w] for s in shards]).reshape(-1)
                for off, w in host_bucket_spans(self.CHUNK, self.WIDTHS)])
            # mesh-major layout: rank-major per bucket column
            for i in range(self.N):
                assert srl[i].tobytes() == pip[i].tobytes()
                got = srl[i].reshape(self.N, self.CHUNK)
                for r in range(self.N):
                    assert np.array_equal(got[r], shards[r])
        finally:
            close_all(chans, engines)

    def test_flat_must_tile_ranks(self):
        peers, chans, engines = make_engines(2, 27860)
        try:
            with pytest.raises(ValueError):
                host_bucket_pipeline(engines[0], np.ones(7, np.float32),
                                     [3], lambda b, r: r)
        finally:
            close_all(chans, engines)

    def test_explicit_bad_depth_rejected(self):
        """depth <= 0 raises the same typed error as set_overlap_depth,
        not a bare IndexError from an empty prefill deque."""
        peers, chans, engines = make_engines(2, 27870)
        try:
            with pytest.raises(ValueError, match="depth"):
                host_bucket_pipeline(engines[0], np.ones(8, np.float32),
                                     [4], lambda b, r: r, depth=0)
            with pytest.raises(ValueError, match="depth"):
                host_bucket_all_gather(engines[0], np.ones(4, np.float32),
                                       [4], depth=0)
        finally:
            close_all(chans, engines)


class TestOverlapDepthBandit:
    def _engine(self, port):
        peers, chans, engines = make_engines(1, port)
        return chans, engines[0]

    def test_explores_then_installs_winner(self):
        chans, eng = self._engine(27880)
        try:
            b = OverlapDepthBandit(eng, depths=(1, 2, 4), check_every=1,
                                   min_pulls=1)
            assert eng.overlap_depth == 1  # first arm installed at start
            # exploration in declaration order; depth 2 measures best
            b.observe(0.5)          # arm "1"
            assert b.active == "2" and eng.overlap_depth == 2
            b.observe(0.1)          # arm "2"
            assert b.active == "4" and eng.overlap_depth == 4
            b.observe(0.6)          # arm "4"
            assert b.active == "2" and eng.overlap_depth == 2
            assert b.swaps >= 2
        finally:
            close_all(chans)

    def test_determinism_identical_streams(self):
        chans, eng = self._engine(27890)
        chans2, eng2 = self._engine(27892)
        try:
            a = OverlapDepthBandit(eng, depths=(1, 2), check_every=1)
            b = OverlapDepthBandit(eng2, depths=(1, 2), check_every=1)
            seq = [0.4, 0.2, 0.3, 0.25, 0.5, 0.2]
            trail_a = [a.observe(s) for s in seq]
            trail_b = [b.observe(s) for s in seq]
            assert trail_a == trail_b and a.active == b.active
        finally:
            close_all(chans)
            close_all(chans2)

    def test_reset_reexplores(self):
        chans, eng = self._engine(27894)
        try:
            b = OverlapDepthBandit(eng, depths=(1, 2), check_every=1)
            b.observe(0.4)
            b.observe(0.1)
            assert b.active == "2"
            b.reset()
            assert b.active == "1" and eng.overlap_depth == 1
        finally:
            close_all(chans)

    def test_rejects_bad_depths(self):
        chans, eng = self._engine(27896)
        try:
            with pytest.raises(ValueError):
                OverlapDepthBandit(eng, depths=())
            with pytest.raises(ValueError):
                OverlapDepthBandit(eng, depths=(0, 2))
        finally:
            close_all(chans)
