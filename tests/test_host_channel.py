"""Control-plane (host channel) and blob-store tests.

Multiple HostChannels on distinct localhost ports inside one process stand
in for multiple worker processes — same trick as the reference's localhost
multi-process integration tests, one level cheaper.
"""

import threading

import pytest

from kungfu_tpu.comm.host import ConnType, HostChannel
from kungfu_tpu.plan import PeerID, PeerList
from kungfu_tpu.store.store import Store, VersionedStore


BASE_PORT = 21000


@pytest.fixture
def channels():
    peers = PeerList.of(*(PeerID("127.0.0.1", BASE_PORT + i) for i in range(3)))
    chans = [HostChannel(p, token=0, bind_host="127.0.0.1") for p in peers]
    yield peers, chans
    for c in chans:
        c.close()


def run_all(fns):
    """Run one closure per simulated peer concurrently; re-raise errors."""
    errors = []
    results = [None] * len(fns)

    def wrap(i, f):
        try:
            results[i] = f()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(i, f)) for i, f in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    if errors:
        raise errors[0]
    return results


class TestHostChannel:
    def test_send_recv(self, channels):
        peers, (a, b, _) = channels
        a.send(peers[1], "hello", b"payload")
        assert b.recv(peers[0], "hello") == b"payload"

    def test_ping(self, channels):
        peers, (a, b, c) = channels
        assert a.ping(peers[1])
        assert a.ping(peers[2])
        assert not a.ping(PeerID("127.0.0.1", 22999), timeout=0.3)

    def test_token_fencing(self, channels):
        peers, (a, b, _) = channels
        b.set_token(5)  # b moved to epoch 5; a still at 0
        a.send(peers[1], "stale", b"x")
        with pytest.raises(TimeoutError):
            b.recv(peers[0], "stale", timeout=0.5)
        # control messages are not fenced
        got = []
        b.on_control(lambda name, payload, src: got.append((name, payload)))
        a.send(peers[1], "update", b"cfg", ConnType.CONTROL)
        import time

        for _ in range(50):
            if got:
                break
            time.sleep(0.05)
        assert got == [("update", b"cfg")]

    def test_barrier(self, channels):
        peers, chans = channels
        run_all([lambda c=c: c.barrier(peers) for c in chans])

    def test_allgather(self, channels):
        peers, chans = channels
        outs = run_all(
            [lambda i=i, c=c: c.allgather_bytes(f"blob{i}".encode(), peers, "ag") for i, c in enumerate(chans)]
        )
        for out in outs:
            assert out == [b"blob0", b"blob1", b"blob2"]

    def test_consensus(self, channels):
        peers, chans = channels
        outs = run_all([lambda c=c: c.consensus_bytes(b"same", peers, "c1") for c in chans])
        assert outs == [True, True, True]
        outs = run_all(
            [lambda i=i, c=c: c.consensus_bytes(b"same" if i < 2 else b"diff", peers, "c2") for i, c in enumerate(chans)]
        )
        assert outs == [False, False, False]


class TestStore:
    def test_size_check(self):
        s = Store()
        s.save("w", b"1234")
        with pytest.raises(ValueError):
            s.save("w", b"12345")
        assert s.get("w") == b"1234"
        assert s.get("missing") is None

    def test_versioned_window(self):
        vs = VersionedStore(window=3)
        for v in range(5):
            vs.save("model", bytes([v] * 4), version=str(v))
        assert vs.versions() == ["2", "3", "4"]
        assert vs.get("model", "1") is None
        assert vs.get("model", "3") == b"\x03\x03\x03\x03"
        assert vs.get("model") == b"\x04\x04\x04\x04"  # latest


class TestP2PStore:
    def test_remote_request(self, channels):
        peers, (a, b, _) = channels
        from kungfu_tpu.store import install_p2p_handler, reset_local_store
        from kungfu_tpu.store.p2p import remote_request
        from kungfu_tpu.store.store import get_local_store

        reset_local_store()
        get_local_store().save("model", b"weights-v0", version="0")
        install_p2p_handler(b)  # b answers from the (shared) local store

        class FakePeer:
            channel = a

            class config:
                self_id = peers[0]

        got = remote_request(FakePeer, peers[1], "model", "0")
        assert got == b"weights-v0"
        assert remote_request(FakePeer, peers[1], "nope") is None
        reset_local_store()
