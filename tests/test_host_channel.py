"""Control-plane (host channel) and blob-store tests.

Multiple HostChannels on distinct localhost ports inside one process stand
in for multiple worker processes — same trick as the reference's localhost
multi-process integration tests, one level cheaper.
"""

import threading
import time

import pytest

from kungfu_tpu.comm.host import (
    ConnType,
    HostChannel,
    NativeHostChannel,
    PyHostChannel,
)
from kungfu_tpu.native import transport as native_transport
from kungfu_tpu.plan import PeerID, PeerList
from kungfu_tpu.store.store import Store, VersionedStore

from tests._util import run_all


BASE_PORT = 21000

_needs_native = pytest.mark.skipif(
    not native_transport.available(), reason="native transport not built"
)

# every backend mix must behave identically — the wire format is shared,
# so a native endpoint and a python endpoint interoperate
BACKENDS = {
    "python": [PyHostChannel] * 3,
    "native": [NativeHostChannel] * 3,
    "mixed": [NativeHostChannel, PyHostChannel, NativeHostChannel],
}


@pytest.fixture(params=list(BACKENDS))
def channels(request):
    if any(c is NativeHostChannel for c in BACKENDS[request.param]):
        if not native_transport.available():
            pytest.skip("native transport not built")
    base = BASE_PORT + 10 * list(BACKENDS).index(request.param)
    peers = PeerList.of(*(PeerID("127.0.0.1", base + i) for i in range(3)))
    chans = [
        cls(p, token=0, bind_host="127.0.0.1")
        for cls, p in zip(BACKENDS[request.param], peers)
    ]
    yield peers, chans
    for c in chans:
        c.close()




class TestHostChannel:
    def test_send_recv(self, channels):
        peers, (a, b, _) = channels
        a.send(peers[1], "hello", b"payload")
        assert b.recv(peers[0], "hello") == b"payload"

    def test_ping(self, channels):
        peers, (a, b, c) = channels
        assert a.ping(peers[1])
        assert a.ping(peers[2])
        assert not a.ping(PeerID("127.0.0.1", 22999), timeout=0.3)

    def test_token_fencing(self, channels):
        peers, (a, b, _) = channels
        b.set_token(5)  # b moved to epoch 5; a still at 0
        a.send(peers[1], "stale", b"x")
        with pytest.raises(TimeoutError):
            b.recv(peers[0], "stale", timeout=0.5)
        # control messages are not fenced
        got = []
        b.on_control(lambda name, payload, src: got.append((name, payload)))
        a.send(peers[1], "update", b"cfg", ConnType.CONTROL)
        import time

        for _ in range(50):
            if got:
                break
            time.sleep(0.05)
        assert got == [("update", b"cfg")]

    def test_recv_into_zero_copy(self, channels):
        """Registered-buffer receive (reference RecvInto/WaitRecvBuf):
        payload lands in the caller's buffer on every backend mix —
        registered-before-arrival AND arrived-before-registration."""
        import numpy as np

        peers, chans = channels
        payload = np.arange(1024, dtype=np.float32)

        # case 1: receiver registers first, sender fires after a delay
        def recv_side():
            buf = np.empty(1024, np.float32)
            ok = chans[1].recv_into(peers[0], "ri1", buf, timeout=30.0)
            assert ok
            np.testing.assert_array_equal(buf, payload)
            return True

        def send_side():
            time.sleep(0.3)
            chans[0].send(peers[1], "ri1", payload.tobytes())
            return True

        assert all(run_all([recv_side, send_side]))

        # case 2: message already queued when recv_into is called
        chans[0].send(peers[1], "ri2", payload.tobytes())
        time.sleep(0.3)
        buf = np.empty(1024, np.float32)
        assert chans[1].recv_into(peers[0], "ri2", buf, timeout=10.0)
        np.testing.assert_array_equal(buf, payload)

        # case 3: size mismatch -> False, payload stays for recv()
        chans[0].send(peers[1], "ri3", payload.tobytes())
        time.sleep(0.3)
        small = np.empty(10, np.float32)
        assert not chans[1].recv_into(peers[0], "ri3", small, timeout=10.0)
        got = chans[1].recv(peers[0], "ri3", timeout=10.0)
        np.testing.assert_array_equal(
            np.frombuffer(got, np.float32), payload
        )

    def test_post_recv_staged(self, channels):
        """Staged receive (round-4 gossip pull shape): the destination
        is registered BEFORE the matching request/response crosses the
        wire — every backend mix must fill the buffer; mismatches fall
        back; abort releases the registration."""
        import numpy as np

        peers, chans = channels
        payload = np.arange(512, dtype=np.float32)

        # registered first, payload arrives later (the zero-copy path
        # on the native backend)
        buf = np.empty(512, np.float32)
        posted = chans[1].post_recv(peers[0], "pr1", buf)
        chans[0].send(peers[1], "pr1", payload)  # buffer-protocol send
        assert posted.wait(timeout=30.0)
        np.testing.assert_array_equal(buf, payload)

        # payload queued before the post: still resolves
        chans[0].send(peers[1], "pr2", payload.tobytes())
        time.sleep(0.3)
        buf2 = np.empty(512, np.float32)
        posted = chans[1].post_recv(peers[0], "pr2", buf2)
        assert posted.wait(timeout=10.0)
        np.testing.assert_array_equal(buf2, payload)

        # size mismatch -> False, payload stays for recv()
        small = np.empty(8, np.float32)
        posted = chans[1].post_recv(peers[0], "pr3", small)
        chans[0].send(peers[1], "pr3", payload.tobytes())
        assert not posted.wait(timeout=10.0)
        got = chans[1].recv(peers[0], "pr3", timeout=10.0)
        np.testing.assert_array_equal(np.frombuffer(got, np.float32), payload)

        # abort: a later send lands in the queue, not the dead buffer
        buf3 = np.zeros(512, np.float32)
        posted = chans[1].post_recv(peers[0], "pr4", buf3)
        posted.abort()
        chans[0].send(peers[1], "pr4", payload.tobytes())
        got = chans[1].recv(peers[0], "pr4", timeout=10.0)
        np.testing.assert_array_equal(np.frombuffer(got, np.float32), payload)
        assert not buf3.any(), "aborted buffer must stay untouched"

    def test_barrier(self, channels):
        peers, chans = channels
        run_all([lambda c=c: c.barrier(peers) for c in chans])

    def test_allgather(self, channels):
        peers, chans = channels
        outs = run_all(
            [lambda i=i, c=c: c.allgather_bytes(f"blob{i}".encode(), peers, "ag") for i, c in enumerate(chans)]
        )
        for out in outs:
            assert out == [b"blob0", b"blob1", b"blob2"]

    def test_consensus(self, channels):
        peers, chans = channels
        outs = run_all([lambda c=c: c.consensus_bytes(b"same", peers, "c1") for c in chans])
        assert outs == [True, True, True]
        outs = run_all(
            [lambda i=i, c=c: c.consensus_bytes(b"same" if i < 2 else b"diff", peers, "c2") for i, c in enumerate(chans)]
        )
        assert outs == [False, False, False]


class TestBackendSelection:
    @_needs_native
    def test_factory_prefers_native(self, monkeypatch):
        monkeypatch.delenv("KF_TPU_HOST_TRANSPORT", raising=False)
        ch = HostChannel(PeerID("127.0.0.1", 21900), bind_host="127.0.0.1")
        try:
            assert isinstance(ch, NativeHostChannel)
        finally:
            ch.close()

    def test_factory_env_forces_python(self, monkeypatch):
        monkeypatch.setenv("KF_TPU_HOST_TRANSPORT", "python")
        ch = HostChannel(PeerID("127.0.0.1", 21901), bind_host="127.0.0.1")
        try:
            assert isinstance(ch, PyHostChannel)
        finally:
            ch.close()

    @_needs_native
    def test_native_ingress_totals(self):
        a, b = PeerID("127.0.0.1", 21902), PeerID("127.0.0.1", 21903)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        cb = NativeHostChannel(b, bind_host="127.0.0.1")
        try:
            ca.send(b, "m", b"x" * 1000)
            assert cb.recv(a, "m") == b"x" * 1000
            assert cb._t.ingress_totals() == {str(a): 1000}
        finally:
            ca.close()
            cb.close()

    @_needs_native
    def test_native_no_fd_leak(self):
        """Pings (fresh connection each) and pool resets must not leak fds."""
        import os
        import time

        a, b = PeerID("127.0.0.1", 21905), PeerID("127.0.0.1", 21906)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        cb = NativeHostChannel(b, bind_host="127.0.0.1")
        try:
            ca.send(b, "warm", b"x")
            cb.recv(a, "warm")
            time.sleep(0.2)
            base = len(os.listdir("/proc/self/fd"))
            for i in range(30):
                ca.ping(b)
                ca.reset_connections()
                ca.send(b, f"m{i}", b"x")
                cb.recv(a, f"m{i}")
            time.sleep(0.5)
            assert len(os.listdir("/proc/self/fd")) - base <= 2
        finally:
            ca.close()
            cb.close()

    @_needs_native
    def test_native_recv_none_timeout_blocks(self):
        """timeout=None must block until data arrives (not instant-timeout)."""
        a, b = PeerID("127.0.0.1", 21907), PeerID("127.0.0.1", 21908)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        cb = NativeHostChannel(b, bind_host="127.0.0.1")
        got = []
        t = threading.Thread(target=lambda: got.append(ca.recv(b, "later", timeout=None)))
        try:
            t.start()
            import time

            time.sleep(0.3)
            assert t.is_alive()
            cb.send(a, "later", b"data")
            t.join(10)
            assert got == [b"data"]
        finally:
            ca.close()
            cb.close()

    @_needs_native
    def test_native_close_while_recv_blocked(self):
        """close() with a blocked receiver must not crash or hang."""
        a = PeerID("127.0.0.1", 21909)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        got = []

        def r():
            try:
                ca.recv(PeerID("127.0.0.1", 21910), "never", timeout=None)
            except ConnectionError:
                got.append("closed")

        t = threading.Thread(target=r)
        t.start()
        import time

        time.sleep(0.2)
        ca.close()
        t.join(10)
        assert got == ["closed"]

    @_needs_native
    def test_native_port_conflict_raises(self):
        a = PeerID("127.0.0.1", 21904)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        try:
            with pytest.raises(OSError):
                native_transport.NativeTransport(str(a), a.port, "127.0.0.1")
        finally:
            ca.close()


class TestUnixSocket:
    def test_sockfile_lifecycle(self):
        import os

        from kungfu_tpu.comm.host import unix_sock_path

        a = PeerID("127.0.0.1", 21920)
        ch = PyHostChannel(a, bind_host="127.0.0.1")
        try:
            assert os.path.exists(unix_sock_path("127.0.0.1", 21920))
        finally:
            ch.close()
        assert not os.path.exists(unix_sock_path("127.0.0.1", 21920))

    def test_colocated_send_uses_unix(self, monkeypatch):
        """With TCP connect disabled, colocated py->py traffic still flows."""
        import socket as socket_mod

        a, b = PeerID("127.0.0.1", 21921), PeerID("127.0.0.1", 21922)
        ca = PyHostChannel(a, bind_host="127.0.0.1")
        cb = PyHostChannel(b, bind_host="127.0.0.1")

        def no_tcp(*args, **kwargs):
            raise AssertionError("TCP used for colocated send")

        monkeypatch.setattr(socket_mod, "create_connection", no_tcp)
        try:
            ca.send(b, "m", b"unix-only")
            assert cb.recv(a, "m") == b"unix-only"
        finally:
            monkeypatch.undo()
            ca.close()
            cb.close()

    def test_disabled_by_env(self, monkeypatch):
        import os

        from kungfu_tpu.comm.host import USE_UNIXSOCK, unix_sock_path

        monkeypatch.setenv(USE_UNIXSOCK, "0")
        a, b = PeerID("127.0.0.1", 21923), PeerID("127.0.0.1", 21924)
        ca = PyHostChannel(a, bind_host="127.0.0.1")
        cb = PyHostChannel(b, bind_host="127.0.0.1")
        try:
            assert not os.path.exists(unix_sock_path("127.0.0.1", 21923))
            ca.send(b, "m", b"tcp")
            assert cb.recv(a, "m") == b"tcp"
        finally:
            ca.close()
            cb.close()

    @_needs_native
    def test_native_unix_interop(self):
        import os

        from kungfu_tpu.comm.host import unix_sock_path

        a, b = PeerID("127.0.0.1", 21925), PeerID("127.0.0.1", 21926)
        ca = NativeHostChannel(a, bind_host="127.0.0.1")
        cb = PyHostChannel(b, bind_host="127.0.0.1")
        try:
            assert os.path.exists(unix_sock_path("127.0.0.1", 21925))  # native sockfile
            ca.send(b, "m", b"n->p")
            assert cb.recv(a, "m") == b"n->p"
            cb.send(a, "m2", b"p->n")
            assert ca.recv(b, "m2") == b"p->n"
        finally:
            ca.close()
            cb.close()
        assert not os.path.exists(unix_sock_path("127.0.0.1", 21925))


class TestStore:
    def test_size_check(self):
        s = Store()
        s.save("w", b"1234")
        with pytest.raises(ValueError):
            s.save("w", b"12345")
        assert s.get("w") == b"1234"
        assert s.get("missing") is None

    def test_versioned_window(self):
        vs = VersionedStore(window=3)
        for v in range(5):
            vs.save("model", bytes([v] * 4), version=str(v))
        assert vs.versions() == ["2", "3", "4"]
        assert vs.get("model", "1") is None
        assert vs.get("model", "3") == b"\x03\x03\x03\x03"
        assert vs.get("model") == b"\x04\x04\x04\x04"  # latest


class TestP2PStore:
    def test_remote_request(self, channels):
        peers, (a, b, _) = channels
        from kungfu_tpu.store import install_p2p_handler, reset_local_store
        from kungfu_tpu.store.p2p import remote_request
        from kungfu_tpu.store.store import get_local_store

        reset_local_store()
        get_local_store().save("model", b"weights-v0", version="0")
        install_p2p_handler(b)  # b answers from the (shared) local store

        class FakePeer:
            channel = a

            class config:
                self_id = peers[0]

        got = remote_request(FakePeer, peers[1], "model", "0")
        assert got == b"weights-v0"
        assert remote_request(FakePeer, peers[1], "nope") is None
        reset_local_store()


class TestLoopbackAliasCluster:
    """Same worker port on two simulated hosts must not alias sockfiles
    (regression: port-only sockfile scheme misdelivered colocated sends)."""

    @pytest.mark.parametrize("cls", [PyHostChannel, NativeHostChannel])
    def test_same_port_two_hosts(self, cls):
        if cls is NativeHostChannel and not native_transport.available():
            pytest.skip("native transport not built")
        p1 = PeerID("127.0.0.1", 21940)
        p2 = PeerID("127.0.0.2", 21940)  # same port, different loopback host
        sender = PeerID("127.0.0.1", 21941)
        c1 = cls(p1, bind_host=p1.host)
        c2 = cls(p2, bind_host=p2.host)
        cs = cls(sender, bind_host=sender.host)
        try:
            cs.send(p1, "m", b"to-host-1")  # colocated -> unix path
            cs.send(p2, "m", b"to-host-2")  # cross-host -> TCP
            assert c1.recv(sender, "m", timeout=10) == b"to-host-1"
            assert c2.recv(sender, "m", timeout=10) == b"to-host-2"
        finally:
            c1.close()
            c2.close()
            cs.close()
